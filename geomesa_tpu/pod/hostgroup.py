"""Host groups: which devices belong to which host, behind two drivers.

A :class:`HostGroup` is the pod tier's layout authority — H hosts, each
contributing a fixed slice of devices, every slice backing one per-host
1-D shard mesh (the same ``parallel.mesh.host_major_slices`` order the
flat ``make_multihost_mesh`` axis uses, so the two views agree on which
host owns which device). Two interchangeable drivers produce the
slices:

- ``distributed`` — a real ``jax.distributed`` multi-process world: one
  host per process, each process's local devices form its slice. Only
  available when the backend supports multi-process collectives;
  :func:`probe_capability` shells out to
  ``scripts/probe_multiprocess.py --json`` for the machine-readable
  supported/UNSUPPORTED verdict, and :func:`make_host_group` raises
  :class:`PodUnsupported` (tests skip, not fail) when the verdict says
  no or the process wasn't launched under ``jax.distributed``.
- ``sim`` — deterministic in-process simulation: the one process's
  devices (the ``--xla_force_host_platform_device_count`` virtual CPU
  mesh on CI) slice host-major into H synthetic hosts. Every pod code
  path — per-host shard builds, cross-host fused dispatch, per-host
  WAL/standing shards — runs identically, so the full matrix pins on
  the CPU CI host.

The group also owns the PER-HOST link profile (ISSUE 20 satellite:
``derive_link_constants`` assumed one link RTT for the whole pod, so
one slow host inflated every host's pad-slot amortization bucket):
:meth:`probe_links` measures each host's pull RTT,
:meth:`set_link_profile` derives one fused slot cap per host through
the shared ``doubling_ladder`` rule, and ``PodIndexTable`` stamps each
shard's ``_slot_cap`` from it — a slow host pays its own bigger bucket,
its peers keep theirs.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np

from geomesa_tpu import conf
from geomesa_tpu.parallel.mesh import SHARD_AXIS, host_major_slices


class PodUnsupported(RuntimeError):
    """The requested host-group driver cannot run in this environment
    (carries the capability-probe reason); tests skip on it, not fail."""


#: memoized capability verdict — the probe spawns two jax.distributed
#: worker processes (~seconds), so one verdict serves the whole process
_PROBE_MEMO: dict = {}


def _probe_script() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        "scripts",
        "probe_multiprocess.py",
    )


def probe_capability(refresh: bool = False) -> dict:
    """The machine-readable multi-process collective verdict:
    ``{"supported": bool, "verdict": "supported"|"UNSUPPORTED"|"error",
    "reason": str}`` from ``scripts/probe_multiprocess.py --json``
    (memoized — the probe launches real subprocesses). The distributed
    driver keys off ``supported``; tests key off ``verdict`` to skip on
    UNSUPPORTED backends instead of failing."""
    if not refresh and "verdict" in _PROBE_MEMO:
        return _PROBE_MEMO["verdict"]
    script = _probe_script()
    if not os.path.exists(script):
        v = {"supported": False, "verdict": "error",
             "reason": f"probe script missing: {script}"}
    else:
        try:
            out = subprocess.run(
                [sys.executable, script, "--json"],
                capture_output=True, text=True, timeout=240,
            )
            lines = [
                ln for ln in out.stdout.splitlines() if ln.strip().startswith("{")
            ]
            v = (
                json.loads(lines[-1])
                if lines
                else {"supported": False, "verdict": "error",
                      "reason": f"no verdict line (rc={out.returncode})"}
            )
        except Exception as e:
            v = {"supported": False, "verdict": "error",
                 "reason": f"probe run failed: {e}"}
    _PROBE_MEMO["verdict"] = v
    return v


class HostGroup:
    """H hosts and their device slices; per-host shard meshes on demand.

    Construct through :func:`make_host_group` (driver/knob resolution)
    — the constructor itself only records a settled layout.
    """

    def __init__(self, driver: str, slices: list):
        if not slices or not slices[0]:
            raise ValueError("a host group needs >= 1 host with >= 1 device")
        widths = {len(s) for s in slices}
        if len(widths) != 1:
            raise ValueError(f"ragged host slices: {sorted(widths)}")
        self.driver = driver
        self.hosts = len(slices)
        self.devices_per_host = len(slices[0])
        self.device_slices = tuple(tuple(s) for s in slices)
        self._meshes: dict = {}
        self._flat_mesh = None
        from geomesa_tpu.lockwitness import witness

        self._probe_lock = witness(
            threading.Lock(), "HostGroup._probe_lock"
        )
        self.link_rtts_ms: list = [None] * self.hosts  # guarded-by: _probe_lock
        self.slot_caps: list = [None] * self.hosts     # guarded-by: _probe_lock

    # -- meshes ----------------------------------------------------------
    def mesh(self, h: int):
        """Host h's 1-D shard mesh over its own device slice (cached):
        the mesh each per-host ``DistributedIndexTable`` shard runs on."""
        from jax.sharding import Mesh

        if h not in self._meshes:
            self._meshes[h] = Mesh(
                np.array(self.device_slices[h]), (SHARD_AXIS,)
            )
        return self._meshes[h]

    def flat_mesh(self):
        """ONE host-major mesh over every device in the group — the
        single-process `DistributedIndexTable` view of the same devices
        (the differential baseline the pod table pins bit-identity
        against, and the equal-device-budget bench comparator)."""
        from jax.sharding import Mesh

        if self._flat_mesh is None:
            flat = [d for s in self.device_slices for d in s]
            self._flat_mesh = Mesh(np.array(flat), (SHARD_AXIS,))
        return self._flat_mesh

    # -- per-host link profile -------------------------------------------
    def set_link_profile(
        self, rtts_ms: list, pull_mb_s: "list | None" = None
    ) -> list:
        """Install per-host measured link RTTs and derive each host's
        fused slot cap through the shared ``derive_link_constants`` /
        ``doubling_ladder`` rule — PER HOST, so one slow host's bigger
        amortization bucket never inflates its peers' pad-slot work.
        Returns the derived caps (None entries keep the design-point
        default for that host)."""
        from geomesa_tpu.scan import block_kernels as bk

        if len(rtts_ms) != self.hosts:
            raise ValueError(f"need {self.hosts} RTTs, got {len(rtts_ms)}")
        caps = []
        for h, rtt in enumerate(rtts_ms):
            if rtt is None:
                caps.append(None)
                continue
            mbps = None if pull_mb_s is None else pull_mb_s[h]
            caps.append(int(bk.derive_link_constants(rtt, mbps)["fused_chunk_slots"]))
        with self._probe_lock:
            self.link_rtts_ms = list(rtts_ms)
            self.slot_caps = caps
        return caps

    def probe_links(self, samples: int = 3) -> list:
        """Measure each host's device->host pull RTT (min over
        ``samples`` small round-trips against the host's first device)
        and install the profile. Gated off by default
        (``geomesa.pod.link.probe``) so tests and CI keep deterministic
        design-point shapes; the bench/pod driver opts in."""
        import jax

        rtts = []
        for h in range(self.hosts):
            dev = self.device_slices[h][0]
            buf = jax.device_put(np.zeros(1024, np.float32), dev)
            jax.block_until_ready(buf)
            best = None
            for _ in range(max(1, samples)):
                t0 = time.perf_counter()
                np.asarray(jax.device_get(buf))
                dt = (time.perf_counter() - t0) * 1e3
                best = dt if best is None else min(best, dt)
            rtts.append(best)
        self.set_link_profile(rtts)
        return rtts

    def slot_cap(self, h: int) -> "int | None":
        with self._probe_lock:
            return self.slot_caps[h]

    def __repr__(self):  # pragma: no cover - debugging aid
        return (
            f"HostGroup(driver={self.driver!r}, hosts={self.hosts}, "
            f"devices_per_host={self.devices_per_host})"
        )


def make_host_group(
    hosts: "int | None" = None,
    devices_per_host: "int | None" = None,
    driver: "str | None" = None,
) -> HostGroup:
    """Resolve a host group from arguments and the ``geomesa.pod.*``
    knobs. ``driver`` is ``"distributed"``, ``"sim"`` or ``"auto"``
    (default: the ``geomesa.pod.driver`` knob): auto picks distributed
    only when this process is part of a multi-process jax world.
    Raises :class:`PodUnsupported` when the distributed driver is
    requested but cannot run here — callers (tests) skip on it."""
    import jax

    driver = (driver or conf.POD_DRIVER.get() or "auto").lower()
    if driver not in ("auto", "sim", "distributed"):
        raise ValueError(f"unknown pod driver {driver!r}")
    procs = int(getattr(jax, "process_count", lambda: 1)())
    if driver == "auto":
        driver = "distributed" if procs > 1 else "sim"

    if driver == "distributed":
        if procs <= 1:
            verdict = probe_capability()
            if verdict.get("supported"):
                raise PodUnsupported(
                    "backend supports multi-process collectives but this "
                    "process was not launched under jax.distributed "
                    "(launch one process per host, then driver=distributed)"
                )
            raise PodUnsupported(
                f"multi-process collectives unavailable: "
                f"{verdict.get('reason', 'probe verdict missing')}"
            )
        hosts = int(hosts or conf.POD_HOSTS.get() or procs)
        if hosts != procs:
            raise ValueError(
                f"distributed driver: hosts={hosts} != process_count={procs}"
            )
        local = jax.local_devices()
        dph = int(devices_per_host or conf.POD_DEVICES_PER_HOST.get() or len(local))
        slices = host_major_slices(jax.devices(), hosts, dph)
    else:
        devs = jax.devices()
        hosts = int(hosts or conf.POD_HOSTS.get() or 0)
        if hosts <= 0:
            raise ValueError(
                "sim driver needs an explicit host count "
                "(hosts= or the geomesa.pod.hosts knob)"
            )
        dph = int(devices_per_host or conf.POD_DEVICES_PER_HOST.get() or 0)
        if dph <= 0:
            if len(devs) < hosts:
                raise PodUnsupported(
                    f"sim driver: {len(devs)} devices cannot back "
                    f"{hosts} one-device hosts"
                )
            dph = len(devs) // hosts
        slices = host_major_slices(devs, hosts, dph)

    group = HostGroup(driver, slices)
    if conf.POD_LINK_PROBE.get():
        group.probe_links()
    return group
