"""PodStore: the streaming story sharded per host.

One :class:`~geomesa_tpu.streaming.store.LambdaStore` runtime PER HOST
— its own cold :class:`~geomesa_tpu.datastore.DataStore` (by default on
that host's shard mesh), its own hot tier, its own WAL directory
(``<root>/host-<h>/_wal``), its own standing-subscription shard. Rows
route by a stable hash of their feature id, so:

- **acks are host-local** — ``write`` returns when each owning host's
  WAL has made the batch durable to its sync policy; no cross-host
  coordination sits on the ack path (``pod.wal.route`` marks each hop);
- **failure is per host** — killing host h loses nothing acknowledged:
  its WAL replay (``rejoin`` -> ``LambdaStore.recover``; the
  ``pod.wal.replay`` fault point) rebuilds exactly the rows and
  standing registrations that host owned — alerts stay at-most-once,
  so an undrained queue dies with its host like any single-process
  crash — and every other host never notices (the chaos matrix pins
  bit-for-bit row equivalence with a never-crashed pod);
- **ingest is host-local** — ``bulk_load`` partitions a collection by
  owner and drives one pipelined ``BulkLoader`` per host against that
  host's cold store: per-host tables sort/build 1/H of the rows on
  their own devices;
- **standing shards compose** — a subscription registers on EVERY
  host's engine, but each acknowledged batch matches only on its
  owning hosts, so the union of per-host alert queues equals the
  single-process matcher's alert set (differential-pinned).

The only pod-global state is the auto-id counter (``_route_lock``,
ranked below every host store lock) — ownership must be decided before
a row can be logged anywhere.
"""

from __future__ import annotations

import os
import threading
import zlib
from typing import Mapping, Optional, Sequence

import numpy as np

from geomesa_tpu.fault import fault_point
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.filter.predicates import INCLUDE
from geomesa_tpu.pod.hostgroup import HostGroup


class PodStore:
    """H host-local streaming runtimes behind one routed facade."""

    def __init__(
        self,
        sft,
        group: HostGroup,
        root: "str | None" = None,
        expiry_ms: Optional[int] = None,
        config=None,
        wal_config=None,
        cold_factory=None,
    ):
        from geomesa_tpu.lockwitness import witness

        self.group = group
        self.hosts = group.hosts
        self.type_name = sft.name
        self._sft_spec = (sft.name, sft.to_spec())
        self.root = root
        self._expiry_ms = expiry_ms
        self._config = config
        self._wal_config = wal_config
        self._cold_factory = cold_factory
        self._route_lock = witness(threading.Lock(), "PodStore._route_lock")
        self._next_id = 0  # guarded-by: _route_lock
        self.stores: list = [self._open_host(h) for h in range(self.hosts)]
        if self.root is not None:
            # seed every host's checkpoint root so a host killed before
            # its first scheduled checkpoint still recovers (replay
            # starts from an empty-but-valid cold store)
            self.checkpoint()

    # -- host runtimes ---------------------------------------------------
    def host_root(self, h: int) -> "str | None":
        return None if self.root is None else os.path.join(self.root, f"host-{h}")

    def host_wal_dir(self, h: int) -> "str | None":
        r = self.host_root(h)
        return None if r is None else os.path.join(r, "_wal")

    def _make_cold(self, h: int):
        from geomesa_tpu.sft import FeatureType

        if self._cold_factory is not None:
            cold = self._cold_factory(h)
        else:
            from geomesa_tpu.datastore import DataStore

            # default: each host's cold table lives on ITS shard mesh
            cold = DataStore(mesh=self.group.mesh(h))
        cold.create_schema(FeatureType.from_spec(*self._sft_spec))
        return cold

    def _open_host(self, h: int):
        from geomesa_tpu.streaming.store import LambdaStore

        wal_dir = self.host_wal_dir(h)
        if wal_dir is not None:
            os.makedirs(wal_dir, exist_ok=True)
        return LambdaStore(
            self._make_cold(h), self.type_name, expiry_ms=self._expiry_ms,
            config=self._config, wal_dir=wal_dir, wal_config=self._wal_config,
        )

    def _require(self, h: int):
        st = self.stores[h]
        if st is None:
            raise RuntimeError(f"pod host {h} is down (rejoin() it first)")
        return st

    # -- ownership -------------------------------------------------------
    def owner(self, fid) -> int:
        """Stable id -> owning host (crc32 mod H): decided at the
        coordinator, identical across restarts and drivers."""
        return zlib.crc32(str(fid).encode()) % self.hosts

    def _route(self, ids: Sequence[str]):
        per: dict[int, list] = {}
        for i, fid in enumerate(ids):
            per.setdefault(self.owner(fid), []).append(i)
        return sorted(per.items())

    # -- mutations (host-local acks) -------------------------------------
    def write(self, rows: Sequence[Mapping], ids: "Sequence[str] | None" = None) -> int:
        """Route a batch to its owning hosts' hot tiers. Each host's
        WAL acknowledges ITS slice (host-local durability); a fault
        between hosts leaves earlier hosts' slices acknowledged and
        later ones not — exactly the per-host ack contract replay
        preserves."""
        rows = list(rows)
        if ids is None:
            with self._route_lock:
                base = self._next_id
                self._next_id += len(rows)
            ids = [f"pod-{base + i}" for i in range(len(rows))]
        else:
            ids = [str(i) for i in ids]
        total = 0
        for h, idxs in self._route(ids):
            fault_point("pod.wal.route")
            total += self._require(h).write(
                [rows[i] for i in idxs], [ids[i] for i in idxs]
            )
        return total

    def delete(self, ids: Sequence[str]) -> int:
        total = 0
        for h, idxs in self._route([str(i) for i in ids]):
            fault_point("pod.wal.route")
            total += self._require(h).delete([str(ids[i]) for i in idxs])
        return total

    def expire(self, now_ms: Optional[int] = None) -> int:
        return sum(self._require(h).expire(now_ms=now_ms) for h in range(self.hosts))

    def bulk_load(self, fc: FeatureCollection, config=None) -> list:
        """Host-local pipelined ingest: partition by owner, one
        ``BulkLoader`` per owning host against that host's cold store
        (each host sorts and uploads only its own 1/H of the rows).
        Returns the per-host ``IngestResult``s (None for hosts that own
        no rows)."""
        from concurrent.futures import ThreadPoolExecutor

        from geomesa_tpu.ingest.pipeline import BulkLoader

        owners = np.array([self.owner(f) for f in fc.ids], np.int64)

        def run(h: int):
            idx = np.flatnonzero(owners == h)
            if not len(idx):
                return None
            fault_point("pod.dispatch")
            loader = BulkLoader(self._require(h).cold, self.type_name, config=config)
            try:
                loader.put(fc.take(idx))
            except BaseException:
                loader.abort()
                raise
            return loader.close()

        with ThreadPoolExecutor(max_workers=self.hosts) as ex:
            return list(ex.map(run, range(self.hosts)))

    # -- standing subscriptions (per-host shards) ------------------------
    def subscribe(self, sub) -> None:
        """Register on EVERY host's engine (each batch only matches on
        its owning host, so the union of shard alerts equals the
        single-process matcher's set). Each host WAL-logs its own copy
        — a recovered host rebuilds its shard from its own log."""
        from geomesa_tpu.streaming.standing import Subscription

        sub.validate()
        rec = sub.to_record()
        for h in range(self.hosts):
            self._require(h).subscribe(Subscription.from_record(rec))

    def unsubscribe(self, sub_id: str) -> bool:
        ok = False
        for h in range(self.hosts):
            ok = self._require(h).unsubscribe(sub_id) or ok
        return ok

    def drain_alerts(self) -> list:
        """Union of the per-host alert queues (order is host-major;
        callers compare as sets — delivery order across hosts is not
        part of the contract, matching the single-process queue's
        batch-order-only guarantee)."""
        out: list = []
        for st in self.stores:
            if st is not None and st._standing is not None:
                out.extend(st.standing().alerts.drain())
        return out

    # -- reads (fan out + disjoint merge) --------------------------------
    def query(self, f=INCLUDE, **kw) -> FeatureCollection:
        parts = [self._require(h).query(f, **kw) for h in range(self.hosts)]
        fault_point("pod.join")
        return FeatureCollection.concat([p for p in parts if len(p)] or parts[:1])

    def count(self, f=INCLUDE) -> int:
        # owners partition ids, so per-host counts add exactly
        total = sum(self._require(h).count(f) for h in range(self.hosts))
        fault_point("pod.join")
        return total

    # -- persistence / failure -------------------------------------------
    def flush(self, incremental: "bool | None" = None, full: bool = False) -> int:
        return sum(
            self._require(h).flush(incremental=incremental, full=full)
            for h in range(self.hosts)
        )

    def checkpoint(self) -> int:
        if self.root is None:
            raise ValueError("PodStore needs a root to checkpoint")
        return sum(
            self._require(h).checkpoint(self.host_root(h))
            for h in range(self.hosts)
        )

    def kill(self, h: int) -> None:
        """Simulate a host crash: abandon the runtime WITHOUT flushing
        or closing — unsynced WAL buffer bytes and the whole hot tier
        vanish (``wal.crash()``, the kill -9 test surface), on-disk WAL
        segments and checkpoints stay — exactly what ``rejoin`` must
        recover from."""
        st = self._require(h)
        if st.wal is not None:
            st.wal.crash()
        self.stores[h] = None

    def rejoin(self, h: int, on_progress=None):
        """Re-open a killed host from its own checkpoint + WAL replay
        (``LambdaStore.recover``): acknowledged rows, standing
        registrations and fold progress return bit-for-bit (undrained
        alerts stay at-most-once and die with the host); the other
        hosts are untouched throughout."""
        from geomesa_tpu.streaming.store import LambdaStore

        if self.stores[h] is not None:
            raise RuntimeError(f"pod host {h} is not down")
        fault_point("pod.wal.replay")
        st = LambdaStore.recover(
            self.host_root(h), type_name=self.type_name,
            wal_dir=self.host_wal_dir(h), expiry_ms=self._expiry_ms,
            config=self._config, wal_config=self._wal_config,
            on_progress=on_progress,
        )
        self.stores[h] = st
        return st

    def close(self) -> None:
        for st in self.stores:
            if st is not None:
                st.close()
