"""PodIndexTable: one index sharded HOST-MAJOR over a host group.

Layout — the block deal is the pod tier's whole argument. The mesh
table (``parallel/dtable.py``) deals blocks round-robin over one flat
device axis, so every query's candidates fan out over every device — the
right call inside one host, where the merge is ICI-cheap. Across hosts
it is exactly wrong: every host touches every query, every host holds
key arrays for the whole table, and ingest re-deals the world. The pod
table instead cuts the globally sorted block sequence into H CONTIGUOUS
runs (the reference's tablet split points, not its in-tablet shards):
host h owns global blocks ``[h*bph, (h+1)*bph)`` and builds ONE per-host
``DistributedIndexTable`` over its own device slice from its slice of
the already-sorted columns (``sorted_state`` identity — no re-sort, and
per-host device memory is ~1/H of the table). A selective query's
candidate blocks then land on FEW hosts; non-owning hosts do zero work.

Execution — the coordinator keeps the global ``SortedKeys`` (ranges,
spans, ``perm``) so planning is bit-identical to the single-process
table, and the device seam routes each candidate-block run to its
owning host's shard: dispatch every owning host first (the per-host
calls are async), then merge on finish. Shard results arrive in
shard-sorted coordinates; adding the host's row base turns them into
global sorted positions, and because cuts are contiguous and ascending
the per-host parts CONCATENATE into globally sorted order — no re-sort
at the coordinator. The fused multi-query path rides the same seam
(``DistributedIndexTable._fused_raw_finishes``): one fused dispatch and
one batched plane pull PER OWNING HOST per chunk, decode at the
coordinator, global ``_post_decode`` — zero XLA recompiles after warmup
and bit-identical results to the flat-mesh table (the differential
tests pin it on both drivers).
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.fault import fault_point
from geomesa_tpu.index.api import IndexKeySpace, ScanConfig, WriteKeys
from geomesa_tpu.parallel.dtable import DistributedIndexTable
from geomesa_tpu.pod.hostgroup import HostGroup
from geomesa_tpu.scan import block_kernels as bk
from geomesa_tpu.storage.table import IndexTable

#: sentinel key values for the rows padding a short host cut (the cut
#: slices sentinel-padded device columns, so only the HOST arrays need
#: explicit pads; values keep the (bin, z) order non-decreasing)
_PAD_BIN = np.int32(np.iinfo(np.int32).max)
_PAD_Z = np.uint64(0xFFFFFFFFFFFFFFFF)


class PodIndexTable(IndexTable):
    """Sorted columnar index cut into per-host contiguous shards, each a
    ``DistributedIndexTable`` on its host's own shard mesh."""

    def __init__(
        self,
        keyspace: IndexKeySpace,
        keys: WriteKeys,
        group: HostGroup,
        tile: int | None = None,
        sorted_state: "np.ndarray | None" = None,
    ):
        self.group = group
        self.hosts = group.hosts
        super().__init__(keyspace, keys, tile=tile, sorted_state=sorted_state)

    # -- layout hooks ----------------------------------------------------
    def _round_blocks(self, n_blocks: int) -> int:
        # multiple of H*dph: the cut is H equal contiguous runs AND each
        # run is a whole number of per-device rounds on its shard mesh,
        # so every global block id (full scans included) maps to a real
        # shard block — the flat-mesh table over the same devices rounds
        # to the same H*dph, which keeps candidate sets identical
        unit = self.hosts * self.group.devices_per_host
        return -(-n_blocks // unit) * unit

    def _place_cols(self, cols: dict, device=None) -> None:
        """Cut the padded sorted columns into H contiguous host runs and
        build one per-host shard table from each — the only device
        placement the pod table does is its shards'."""
        self.rows_uploaded = self.n_pad
        H = self.hosts
        self.blocks_per_host = self.n_blocks // H
        rows_ph = self.blocks_per_host * self.block
        self.rows_per_host = rows_ph
        self.cols3 = {}  # per-host shards own the device arrays
        self._col_bytes = {k: int(v.dtype.itemsize) for k, v in cols.items()}
        self.shards: list[DistributedIndexTable] = []
        for h in range(H):
            r0 = h * rows_ph
            n_h = max(0, min(self.n - r0, rows_ph))  # real rows in the cut
            bins = np.full(rows_ph, _PAD_BIN, np.int32)
            zs = np.full(rows_ph, _PAD_Z, np.uint64)
            bins[:n_h] = self.bins[r0 : r0 + n_h]
            zs[:n_h] = self.zs[r0 : r0 + n_h]
            sub = None
            if self.subkeys is not None:
                sub = np.zeros(
                    (rows_ph, self.subkeys.shape[1]), self.subkeys.dtype
                )
                sub[:n_h] = self.subkeys[r0 : r0 + n_h]
            shard_keys = WriteKeys(
                bins=bins,
                zs=zs,
                # the pod-level pad already wrote never-matching
                # sentinels past row n, so a short cut's tail rows are
                # sentinels by construction
                device_cols={k: v[r0 : r0 + rows_ph] for k, v in cols.items()},
                sub=sub,
            )
            shard = DistributedIndexTable(
                self.keyspace,
                shard_keys,
                self.group.mesh(h),
                tile=self.block,
                # the cut slices the globally sorted columns: identity
                # order, no per-shard re-sort
                sorted_state=np.arange(rows_ph, dtype=np.int64),
            )
            cap = self.group.slot_cap(h)
            if cap is not None:
                shard._slot_cap = cap  # per-host probed link (satellite)
            self.shards.append(shard)

    # -- accounting (no coordinator-resident device columns) -------------
    def _record_scan(self, names: tuple, n_blocks: int) -> None:
        self.last_scan_cols = names
        self.last_scan_bytes = sum(
            self._col_bytes[k] for k in names
        ) * n_blocks * self.block

    @property
    def nbytes_device(self) -> int:
        return sum(sh.nbytes_device for sh in self.shards)

    def warmup(self) -> int:
        """Per-shard warmup: the pod table has no kernels of its own —
        every variant it can hit is a shard variant on that host's mesh."""
        return sum(sh.warmup() for sh in self.shards)

    # -- ownership routing -----------------------------------------------
    def _host_blocks(self, blocks: np.ndarray):
        """Ascending global candidate blocks -> [(h, local_blocks)] over
        OWNING hosts only (the contiguous cut makes this two
        searchsorted calls per host; non-owning hosts never appear)."""
        bph = self.blocks_per_host
        out = []
        for h in range(self.hosts):
            s = int(np.searchsorted(blocks, h * bph))
            e = int(np.searchsorted(blocks, (h + 1) * bph))
            if e > s:
                out.append((h, blocks[s:e] - h * bph))
        return out

    def _merge_host_rows(self, parts):
        """[(h, shard_rows, certain)] in ascending host order -> global
        (rows, certain): shard rows + the host's row base are global
        sorted positions, and contiguous ascending cuts concatenate
        already sorted."""
        fault_point("pod.join")
        parts = [
            (h, r, c) for h, r, c in parts if len(r)
        ]
        if not parts:
            return np.zeros(0, np.int64), np.zeros(0, bool)
        rows = np.concatenate([
            r.astype(np.int64) + h * self.rows_per_host for h, r, _ in parts
        ])
        cert = np.concatenate([c for _, _, c in parts])
        return rows, cert

    # -- device hooks ------------------------------------------------------
    def _device_scan_submit(self, blocks: np.ndarray, config: ScanConfig):
        per_host = self._host_blocks(blocks)
        names = self._scan_cols(config)
        self._record_scan(names, int(sum(len(loc) for _, loc in per_host)))
        pending = []
        for h, loc in per_host:
            fault_point("pod.dispatch")
            # dispatch every owning host before finishing any: the
            # shard calls are async, so H hosts scan concurrently
            pending.append((h, self.shards[h]._device_scan_submit(loc, config)))

        def finish():
            return self._merge_host_rows(
                [(h, *fin()) for h, fin in pending]
            )

        return finish

    def _device_pops(self, blocks: np.ndarray, config: ScanConfig):
        per_host = self._host_blocks(blocks)
        pops_parts: list = []
        gbid_parts: list = []
        for h, loc in per_host:
            fault_point("pod.dispatch")
            pops, gbids = self.shards[h]._device_pops(loc, config)
            pops_parts.append(pops)
            gbid_parts.append(gbids + h * self.blocks_per_host)
        fault_point("pod.join")
        if not pops_parts:
            return np.zeros(0, np.int64), np.zeros(0, np.int64)
        # per-shard results are gbid-sorted; ascending host cuts keep the
        # concatenation globally sorted
        return np.concatenate(pops_parts), np.concatenate(gbid_parts)

    def _device_density_submit(self, blocks, config, grid_bounds, width, height):
        per_host = self._host_blocks(blocks)
        finishes = []
        for h, loc in per_host:
            fault_point("pod.dispatch")
            finishes.append(
                self.shards[h]._device_density_submit(
                    loc, config, grid_bounds, width, height
                )
            )

        def finish():
            fault_point("pod.join")
            grid = np.zeros((height, width), np.float32)
            for fin in finishes:
                grid = grid + fin()
            return grid

        return finish

    def _device_bounds(self, blocks, config):
        per_host = self._host_blocks(blocks)
        total, env = 0, None
        for h, loc in per_host:
            fault_point("pod.dispatch")
            cnt, e = self.shards[h]._device_bounds(loc, config)
            total += cnt
            if e is not None:
                env = e if env is None else (
                    min(env[0], e[0]), min(env[1], e[1]),
                    max(env[2], e[2]), max(env[3], e[3]),
                )
        fault_point("pod.join")
        return total, env

    # -- fused multi-query scan (cross-host leg) -------------------------
    @property
    def fused_slots(self) -> int:
        return min(sh.fused_slots for sh in self.shards)

    @property
    def fused_pack_capacity(self) -> int:
        return sum(sh.fused_pack_capacity for sh in self.shards)

    def _submit_fused_chunk(
        self, members, names, has_boxes, has_windows, finishes, deadline
    ):
        """Cross-host fused dispatch: route each member's candidate
        blocks to owning hosts, pre-check every host's per-device slot
        budget (a skewed chunk splits BEFORE any host dispatches — no
        wasted legs), then drive each owning host's
        ``_fused_raw_finishes`` — one fused kernel call and one batched
        plane pull per host per chunk. Members decode per host at the
        coordinator; the global ``_post_decode`` runs once per member,
        so results stay bit-identical to the flat-mesh fused path."""
        if self._fused_route_single(members, finishes, deadline):
            return
        host_members: dict[int, list] = {}
        for k, m in enumerate(members):
            for h, loc in self._host_blocks(m[2]):
                host_members.setdefault(h, []).append((k, loc))
        for h, mem in host_members.items():
            sh = self.shards[h]
            counts = np.zeros(sh.n_devices, np.int64)
            for _, loc in mem:
                counts += np.bincount(
                    loc % sh.n_devices, minlength=sh.n_devices
                )
            if counts.max() > sh.fused_slots:
                self._split_fused_chunk(
                    members, names, has_boxes, has_windows, finishes, deadline
                )
                return
        host_raw: list = []
        for h in sorted(host_members):
            fault_point("pod.dispatch")
            mem = host_members[h]
            sub_members = [
                (i, members[k][1], loc, (), ())
                for i, (k, loc) in enumerate(mem)
            ]
            raw = self.shards[h]._fused_raw_finishes(
                sub_members, names, has_boxes, has_windows, deadline
            )
            if raw is None:  # defensive: the pre-check mirrors this test
                self._split_fused_chunk(
                    members, names, has_boxes, has_windows, finishes, deadline
                )
                return
            host_raw.append(
                (h, {k: raw[i] for i, (k, _) in enumerate(mem)})
            )

        def member_finish(k):
            j, config, blocks, overlap, contained = members[k]
            parts = []
            for h, raws in host_raw:
                fn = raws.get(k)
                if fn is not None:
                    parts.append((h, *fn()))
            rows, certain = self._merge_host_rows(parts)
            return self._post_decode(rows, certain, config, overlap, contained)

        for k, (j, *_rest) in enumerate(members):
            finishes[j] = lambda k=k: member_finish(k)

    def _split_fused_chunk(
        self, members, names, has_boxes, has_windows, finishes, deadline
    ):
        """Half-split recursion on slot overflow (the dtable policy,
        hoisted so the pre-check and the defensive path share it);
        bottoms out at the per-query route."""
        half = len(members) // 2
        self._submit_fused_chunk(
            members[:half], names, has_boxes, has_windows, finishes, deadline
        )
        self._submit_fused_chunk(
            members[half:], names, has_boxes, has_windows, finishes, deadline
        )
