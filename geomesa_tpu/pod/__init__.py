"""Multi-host pod tier: host-group abstraction over the mesh scan path.

The reference GeoMesa scales by spreading tablets over Accumulo/HBase
region servers; this package is the TPU-pod analogue. A
:class:`~geomesa_tpu.pod.hostgroup.HostGroup` names H hosts and their
per-host device slices behind two interchangeable drivers (a real
``jax.distributed`` multi-process world, or a deterministic in-process
simulation over local device slices), a
:class:`~geomesa_tpu.pod.table.PodIndexTable` deals the sorted table's
blocks HOST-MAJOR so each host owns one contiguous shard (per-host
memory ~1/H, selective queries dispatch only to owning hosts), and a
:class:`~geomesa_tpu.pod.store.PodStore` shards the streaming story —
per-host WAL + hot tier with host-local acks, host-local pipelined
ingest, per-host standing-subscription shards. See docs/distributed.md.
"""

from geomesa_tpu.pod.hostgroup import (
    HostGroup,
    PodUnsupported,
    make_host_group,
    probe_capability,
)
from geomesa_tpu.pod.table import PodIndexTable
from geomesa_tpu.pod.store import PodStore

__all__ = [
    "HostGroup",
    "PodIndexTable",
    "PodStore",
    "PodUnsupported",
    "make_host_group",
    "probe_capability",
]
