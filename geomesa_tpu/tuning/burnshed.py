"""SLO-burn-driven admission shedding (docs/tuning.md, ISSUE 19 leg c).

The scheduler today sheds on PHYSICAL pressure only: a full queue or
a deadline that cannot survive the batch window. But an SLO burning
its error budget is an earlier, cheaper signal — by the time the
queue is full, p99 is already blown. This gate watches the attached
:class:`~geomesa_tpu.obs.slo.SloTracker`'s burn rate for one declared
objective and, while it burns past threshold, sheds the LOW-PRIORITY
slice of incoming work: tenants whose DRR weight sits strictly below
the heaviest configured weight (PR 17's fairness tiers double as the
priority order; a store with uniform weights sheds nothing — burn
shedding must never starve an undifferentiated workload).

Engagement is hysteretic: engage when burn > ``threshold``, release
only when burn <= ``release`` (default 1.0 = exactly on budget), so
a burn rate oscillating around the threshold cannot flap admission.

Concurrency: the gate is called on the scheduler's submit path BEFORE
``QueryScheduler._cond`` is taken, and holds NO lock of its own — its
whole state is one tuple swapped atomically (readers see the old or
the new snapshot, both consistent). The refresh itself reads the SLO
tracker and tenant registry (their own locks, never nested under
anything) and is throttled so a hot submit path costs a monotonic
clock read, not a report.
"""

from __future__ import annotations

import time
from typing import Optional


class BurnShed:
    """Admission gate fed by SLO burn rate + tenant weights. Built and
    wired by :class:`~geomesa_tpu.tuning.manager.TuningManager`; the
    scheduler only calls :meth:`should_shed`."""

    def __init__(
        self,
        store,
        objective: str = "query_p99",
        threshold: float = 2.0,
        release: float = 1.0,
        refresh_s: float = 0.05,
    ):
        self.store = store
        self.objective = objective
        self.threshold = float(threshold)
        self.release = float(release)
        self.refresh_s = float(refresh_s)
        # (burn_rate, engaged, weights_snapshot, max_weight) — swapped
        # whole; the ONLY mutable state besides the refresh clock
        self._state: "tuple[float, bool, dict, float]" = (0.0, False, {}, 0.0)
        self._next_refresh = 0.0

    # -- sensing ----------------------------------------------------------
    def _burn(self, now) -> float:
        slo = getattr(self.store, "slo", None)
        if slo is None:
            return 0.0
        for row in slo.report(now)["objectives"]:
            if row.get("objective") == self.objective:
                return float(row.get("burn_rate") or 0.0)
        return 0.0

    def refresh(self, now=None) -> None:
        """Re-read burn + weights if the throttle window elapsed.
        ``now`` is a test seam passed through to ``SloTracker.report``;
        the throttle always uses the monotonic clock."""
        mono = time.monotonic()
        if mono < self._next_refresh and now is None:
            return
        self._next_refresh = mono + self.refresh_s
        burn = self._burn(now)
        _, engaged, _, _ = self._state
        if engaged:
            engaged = burn > self.release  # release hysteresis
        else:
            engaged = burn > self.threshold
        weights: dict = {}
        max_w = 0.0
        if engaged:
            sched = getattr(self.store, "scheduler", None)
            tenants = getattr(sched, "tenants", None)
            if tenants is not None:
                weights = tenants.weights()
                if weights:
                    max_w = max(weights.values())
        self._state = (burn, engaged, weights, max_w)

    # -- the submit-path read --------------------------------------------
    def should_shed(self, tenant: Optional[str], now=None) -> Optional[str]:
        """Reason string when this tenant's work should shed under the
        current burn, else None. Called with no lock held."""
        self.refresh(now)
        burn, engaged, weights, max_w = self._state
        if not engaged or not weights:
            return None
        from geomesa_tpu.serving.tenancy import PUBLIC_TENANT

        tid = tenant if tenant is not None else PUBLIC_TENANT
        w = weights.get(tid)
        if w is None:
            # never-seen tenant: default weight (matches the registry's
            # lazy materialization — it would get this weight on first
            # touch)
            from geomesa_tpu import conf

            w = float(conf.TENANT_DEFAULT_WEIGHT.get())
        if w >= max_w:
            return None  # top-priority work always admits
        return (
            f"slo burn {burn:.2f}x > {self.threshold:.2f}x on "
            f"{self.objective}: tenant {tid!r} weight {w:g} below max {max_w:g}"
        )

    def report(self) -> dict:
        burn, engaged, weights, max_w = self._state
        return {
            "objective": self.objective,
            "threshold": self.threshold,
            "release": self.release,
            "burn": round(burn, 4),
            "engaged": engaged,
            "max_weight": max_w,
            "weights": dict(weights),
        }
