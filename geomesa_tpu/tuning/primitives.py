"""Shared measured-cost controller primitives (docs/tuning.md).

Four independent feedback gates grew hand-rolled before this tier
existed: the tile compose cost gate (cache/tiles.py), the adaptive
join gate from arXiv 1802.09488 (sql/join.py), standing's
host-vs-fused match gate (streaming/standing.py), and the bench link
probe's constant derivation (scan/block_kernels.py). They all reduce
to three moves — blend a measured per-unit cost into an EWMA, back
off with periodic re-probes after losing, and snap a continuous
target onto a power-of-two ladder. This module IS those moves,
extracted once; the gates import from here and their decisions stay
bit-identical on their test matrices (pinned by the differential
tests in tests/test_tuning.py).

Everything here is lock-free plain arithmetic: callers own the
synchronization (each gate keeps its own lock and rank, see
analysis/lockmodel.py), so these primitives never nest locks.
"""

from __future__ import annotations

from typing import Optional

# one smoothing constant store-wide: all four pre-existing gates
# independently picked 0.25 (the 1802.09488 choice: heavy enough to
# react within ~4 observations, light enough to ride out one outlier)
DEFAULT_ALPHA = 0.25


def ewma_step(
    prev: Optional[float], sample: float, alpha: float = DEFAULT_ALPHA
) -> float:
    """One EWMA blend: the first sample seeds the average, later ones
    fold in at weight ``alpha``. The canonical ``(1-a)*prev + a*s``
    form (what join/_MatchGate always computed; the tile gate's
    algebraically-equal nudge form migrated onto it)."""
    if prev is None:
        return sample
    return (1.0 - alpha) * prev + alpha * sample


class CostEwma:
    """A measured per-unit cost average: seconds/unit blended at
    ``alpha``. ``value`` is None until the first accepted sample —
    callers distinguish "never measured" (probe!) from "measured
    cheap". Non-positive samples are dropped, not averaged: a clock
    that returned 0 or a batch of 0 units carries no cost signal
    (the exact guard every pre-migration gate applied)."""

    __slots__ = ("alpha", "value")

    def __init__(self, alpha: float = DEFAULT_ALPHA):
        self.alpha = alpha
        self.value: Optional[float] = None

    def update(self, sample: float) -> float:
        self.value = ewma_step(self.value, float(sample), self.alpha)
        return self.value

    def update_cost(self, seconds: float, units: float) -> Optional[float]:
        if units <= 0 or seconds <= 0:
            return self.value
        return self.update(seconds / units)

    def value_or(self, prior: float) -> float:
        """The measured average, or ``prior`` before any sample — how
        the gates fold a design-point cost constant into their first
        decisions."""
        return prior if self.value is None else self.value


class ProbeGate:
    """Explore-then-reprobe admission for a measured alternative: let
    the first ``explore_min`` trials through unconditionally (the
    EWMAs need samples before they mean anything), then, once the
    measurement says "losing", still let every ``reprobe_every``-th
    blocked attempt through so a workload shift can win the gate back.
    Exactly the tile gate's ``_compose_n``/``_gated`` counters,
    extracted."""

    __slots__ = ("explore_min", "reprobe_every", "trials", "blocked")

    def __init__(self, explore_min: int, reprobe_every: int):
        self.explore_min = explore_min
        self.reprobe_every = reprobe_every
        self.trials = 0   # measured attempts let through so far
        self.blocked = 0  # consecutive losses since the last re-probe

    @property
    def exploring(self) -> bool:
        return self.trials < self.explore_min

    def note_trial(self) -> None:
        """One measured attempt completed (its cost fed the EWMA)."""
        self.trials += 1

    def block(self) -> bool:
        """Record one losing decision. True = let this attempt through
        anyway (the periodic re-probe, resetting the streak); False =
        actually gate it."""
        self.blocked += 1
        if self.blocked >= self.reprobe_every:
            self.blocked = 0
            return True
        return False


def doubling_ladder(want: float, base: int, cap: int) -> int:
    """Snap a continuous target onto the power-of-two ladder from
    ``base`` up to ``cap``: the smallest rung >= ``want`` (``cap``
    when the target overshoots it). Bit-identical to the link probe's
    original slot loop — device-side buffer sizes must stay on the
    compiled bucket grid, so controllers never write an off-ladder
    value."""
    step = base
    while step < want and step < cap:
        step *= 2
    return step
