"""Self-tuning controller tier (docs/tuning.md): the loop that turns
the store's existing telemetry — estimate-accuracy windows, live
histograms and counters, SLO burn rates, link probe constants — into
bounded online decisions. ``DataStore.attach_tuning()`` is the entry
point; ``geomesa.tuning.enabled`` arms it; disarmed behavior is
bit-identical to a store without this package."""

from geomesa_tpu.tuning.burnshed import BurnShed
from geomesa_tpu.tuning.controllers import (
    CONTROLLER_SPECS,
    ControllerSpec,
    KnobController,
)
from geomesa_tpu.tuning.manager import TuningManager
from geomesa_tpu.tuning.primitives import (
    DEFAULT_ALPHA,
    CostEwma,
    ProbeGate,
    doubling_ladder,
    ewma_step,
)
from geomesa_tpu.tuning.reweight import IndexReweighter

__all__ = [
    "BurnShed",
    "CONTROLLER_SPECS",
    "ControllerSpec",
    "CostEwma",
    "DEFAULT_ALPHA",
    "IndexReweighter",
    "KnobController",
    "ProbeGate",
    "TuningManager",
    "doubling_ladder",
    "ewma_step",
]
