"""The store's self-tuning loop (docs/tuning.md).

One :class:`TuningManager` per DataStore closes ISSUE 19's loop: the
sensors the store already carries (EstimateAccuracy windows, the live
metric histograms/counters, the SLO tracker's burn rates, the link
probe constants) feed three actuator legs — plan-feedback index
reweighting (reweight.py), bounded knob hill-climbs (controllers.py)
and SLO-burn admission shedding (burnshed.py). ``DataStore.
attach_tuning()`` builds and wires one; ``geomesa.tuning.enabled``
arms it. DISARMED IS FREE: an unarmed manager never pulses, the
planner/scheduler hooks stay ``None``, and the store's behavior is
bit-identical to a build without this package (pinned by
tests/test_tuning.py's differential suite).

Pacing and concurrency: the loop piggybacks on the query path —
``DataStore.record_query`` calls :meth:`on_query`, and every
``geomesa.tuning.interval``-th query runs one :meth:`pulse` in that
caller's thread (no tuner thread to leak; an idle store never tunes,
which is correct — there is nothing to adapt to). ``TuningManager.
_lock`` is a strict LEAF: it guards only the counters and the
decision ring, and NOTHING else is ever acquired while it is held —
all sensing (accuracy lock, metrics lock, SLO lock) happens outside
it, and a claim flag serializes concurrent pulses without blocking
them. Every adaptation lands in the bounded decision ring with its
reason, under a ``tuning.adjust`` span and ``geomesa.tuning.*``
counters, and is served verbatim by ``GET /debug/tuning`` and
``geomesa tune`` — the audit trail for a store that changes its own
configuration.
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Optional

from geomesa_tpu.obs.trace import span as _ospan
from geomesa_tpu.tuning.burnshed import BurnShed
from geomesa_tpu.tuning.controllers import CONTROLLER_SPECS, KnobController
from geomesa_tpu.tuning.reweight import IndexReweighter


class TuningManager:
    """Controller tier for one DataStore: owns the reweighter, the
    knob controllers and the burn gate; paces pulses; keeps the
    decision audit ring; persists learned state across close/reopen."""

    def __init__(
        self,
        store,
        enabled: Optional[bool] = None,
        state_path: Optional[str] = None,
        interval: Optional[int] = None,
    ):
        from geomesa_tpu import conf
        from geomesa_tpu.lockwitness import witness

        self.store = store
        self.enabled = (
            bool(conf.TUNING_ENABLED.get()) if enabled is None
            else bool(enabled)
        )
        self.state_path = state_path
        self.interval = max(
            1, int(interval if interval is not None
                   else conf.TUNING_INTERVAL.get())
        )
        self._lock = witness(threading.Lock(), "TuningManager._lock")
        self._queries = 0   # guarded-by: _lock
        self._pulses = 0    # guarded-by: _lock
        self._pulsing = False  # guarded-by: _lock (pulse claim flag)
        keep = max(1, int(conf.TUNING_DECISIONS.get()))
        self._decisions: "deque[dict]" = deque(maxlen=keep)  # guarded-by: _lock
        # single-writer state (only the thread holding the pulse claim
        # touches these between claim and release): counter baselines
        # and the latest objective reading per controller
        self._last_raw: "dict[str, int]" = {}
        self._last_reading: "dict[str, float]" = {}
        self.reweighter = IndexReweighter(
            store.accuracy,
            max_adjust=float(conf.TUNING_PLAN_MAX_ADJUST.get()),
            deadband=float(conf.TUNING_PLAN_DEADBAND.get()),
            min_count=int(conf.TUNING_PLAN_MIN_COUNT.get()),
        )
        self.burnshed = BurnShed(
            store,
            objective=str(conf.TUNING_BURN_OBJECTIVE.get()),
            threshold=float(conf.TUNING_BURN_THRESHOLD.get()),
            release=float(conf.TUNING_BURN_RELEASE.get()),
        )
        self.controllers = {s.name: KnobController(s) for s in CONTROLLER_SPECS}
        if state_path:
            self.load()

    # -- pacing -----------------------------------------------------------
    def on_query(self) -> None:
        """Query-path hook (DataStore.record_query): count, and run one
        pulse every ``interval``-th query in this caller's thread."""
        if not self.enabled:
            return
        with self._lock:
            self._queries += 1
            due = self._queries % self.interval == 0
        if due:
            self.pulse()

    # -- the control step -------------------------------------------------
    def pulse(self, now=None) -> "list[dict]":
        """One adaptation step across all three legs. Concurrent calls
        collapse to one (claim flag); the loser returns immediately —
        a skipped pulse costs nothing, the next interval retries."""
        if not self.enabled:
            return []
        with self._lock:
            if self._pulsing:
                return []
            self._pulsing = True
        try:
            return self._pulse_locked_out(now)
        finally:
            with self._lock:
                self._pulsing = False

    def _pulse_locked_out(self, now) -> "list[dict]":
        metrics = self.store.metrics
        with _ospan("tuning.adjust"):
            if metrics is not None:
                metrics.counter("geomesa.tuning.pulse")
            decisions: "list[dict]" = []
            # leg (a): plan-feedback reweighting off the accuracy report
            plan_moves = self.reweighter.pulse()
            if plan_moves and metrics is not None:
                metrics.counter("geomesa.tuning.reweight", len(plan_moves))
            decisions.extend(plan_moves)
            # leg (b): bounded knob controllers off the telemetry rings
            for spec in CONTROLLER_SPECS:
                d = self._step_controller(spec, metrics)
                if d is not None:
                    decisions.append(d)
            # leg (c): refresh the burn gate's snapshot (the scheduler
            # reads it lock-free on every submit) + export the gauge
            self.burnshed.refresh(now)
            if metrics is not None:
                metrics.gauge(
                    "geomesa.tuning.burn", self.burnshed.report()["burn"]
                )
        with self._lock:
            self._pulses += 1
            self._decisions.extend(decisions)
        return decisions

    def _step_controller(self, spec, metrics) -> Optional[dict]:
        from geomesa_tpu import conf

        prop = conf.REGISTRY.get(spec.knob)
        if prop is None:
            return None
        reading = self._reading(spec, metrics)
        if reading is None:
            return None
        self._last_reading[spec.name] = reading
        current = float(prop.get() or 0.0)
        if spec.policy == "derive":
            # closed-form: the link probe's ladder, re-derived from the
            # live RTT gauge (reading) instead of a one-shot bench probe
            from geomesa_tpu.scan import block_kernels as bk

            derived = bk.derive_link_constants(reading)["fused_chunk_slots"]
            nxt = float(min(spec.hi, max(spec.lo, derived)))
            if current == nxt or (current == 0.0 and bk.fused_slot_cap() == int(nxt)):
                return None  # auto path already lands there: hold
            why = (
                f"link rtt {reading:.2f}ms -> {int(nxt)} slots on the "
                f"doubling ladder"
            )
        else:
            ctl = self.controllers[spec.name]
            proposed = ctl.propose(current, reading)
            if proposed is None:
                return None
            nxt = proposed
            why = (
                f"objective {spec.objective} read {reading:.6g} "
                f"({'higher' if spec.higher_is_better else 'lower'} is "
                f"better): step {current:.6g} -> {nxt:.6g} within "
                f"[{spec.lo:g}, {spec.hi:g}]"
            )
        return self._apply(spec, current, nxt, why, metrics)

    def _reading(self, spec, metrics) -> Optional[float]:
        """Resolve one objective reading; None = no signal this pulse
        (unseeded counter baseline, never-observed histogram, no link
        probe yet) — the controller holds rather than moves blind."""
        if spec.objective_kind == "gauge":
            # the link gauge is OURS to sense: exported from the scan
            # tier's probed constants so it exists as a real metric
            from geomesa_tpu.scan import block_kernels as bk

            rtt = bk.link_constants().get("link_rtt_ms")
            if rtt is None:
                return None
            if metrics is not None:
                metrics.gauge("geomesa.tuning.link.rtt", float(rtt))
            return float(rtt)
        if metrics is None:
            return None
        if spec.objective_kind == "counter":
            raw = metrics.counter_value(spec.objective)
            last = self._last_raw.get(spec.name)
            self._last_raw[spec.name] = raw
            if last is None:
                return None  # first pulse seeds the delta baseline
            return float(raw - last)
        v = metrics.histogram_quantile(spec.objective, 0.99)
        return v if v > 0.0 else None

    def _apply(self, spec, old: float, new: float, why: str, metrics) -> dict:
        """Write one decision through ``conf`` plus the live objects
        that snapshot their config at construction — a knob nobody
        re-reads is not an actuation."""
        from geomesa_tpu import conf

        value = int(new) if spec.integral else float(new)
        conf.REGISTRY[spec.knob].set(value)
        if spec.name == "cache_min_cost":
            cache = getattr(self.store, "cache", None)
            result = getattr(cache, "result", None)
            if result is not None:
                # ResultCacheConf is snapshot at attach time: write the
                # live threshold too, or the running cache keeps judging
                # admissions by the old floor
                result.conf.min_cost_s = float(new)
        if metrics is not None:
            metrics.counter("geomesa.tuning.adjust")
        return {
            "controller": spec.name,
            "knob": spec.knob,
            "from": old,
            "to": value,
            "reason": why,
        }

    # -- observability ----------------------------------------------------
    def report(self) -> dict:
        """The ``/debug/tuning`` + ``geomesa tune`` payload: every
        controller's current value/bounds/objective reading, the plan
        factor table, the burn gate state, and the decision ring."""
        from geomesa_tpu import conf

        with self._lock:
            queries, pulses = self._queries, self._pulses
            decisions = list(self._decisions)
        readings = dict(self._last_reading)
        rows = []
        for spec in CONTROLLER_SPECS:
            prop = conf.REGISTRY.get(spec.knob)
            rows.append({
                "name": spec.name,
                "knob": spec.knob,
                "value": prop.get() if prop is not None else None,
                "lo": spec.lo,
                "hi": spec.hi,
                "objective": spec.objective,
                "objective_kind": spec.objective_kind,
                "policy": spec.policy,
                "reading": readings.get(spec.name),
                "doc": spec.doc,
            })
        return {
            "enabled": self.enabled,
            "interval": self.interval,
            "queries": queries,
            "pulses": pulses,
            "controllers": rows,
            "plan_factors": {
                f"{t}/{i}": round(f, 4)
                for (t, i), f in sorted(self.reweighter.factors().items())
            },
            "burn": self.burnshed.report(),
            "decisions": decisions,
        }

    # -- persistence (close/reopen without re-learning) -------------------
    def state(self) -> dict:
        from geomesa_tpu import conf

        with self._lock:
            decisions = list(self._decisions)
        return {
            "factors": self.reweighter.snapshot(),
            "controllers": {
                name: ctl.snapshot() for name, ctl in self.controllers.items()
            },
            "knobs": {
                spec.knob: conf.REGISTRY[spec.knob].get()
                for spec in CONTROLLER_SPECS
                if spec.knob in conf.REGISTRY
            },
            "decisions": decisions[-16:],
        }

    def save(self) -> None:
        """Persist learned state next to the catalog (atomic rename);
        DataStore.close() calls this when a state path was given."""
        if not self.state_path:
            return
        tmp = self.state_path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(self.state(), fh, indent=2, sort_keys=True)
            os.replace(tmp, self.state_path)
        except OSError:  # pragma: no cover - state file is best-effort
            pass

    def load(self) -> None:
        """Rehydrate from :meth:`save` output: factor table, controller
        baselines and the tuned knob values re-applied — a reopened
        store starts from what it learned, not from zero."""
        if not self.state_path or not os.path.exists(self.state_path):
            return
        try:
            with open(self.state_path, encoding="utf-8") as fh:
                state = json.load(fh)
        except (OSError, ValueError):  # pragma: no cover - corrupt state
            return  # a bad state file means re-learning, never failing
        from geomesa_tpu import conf

        self.reweighter.restore(state.get("factors") or [])
        saved = state.get("controllers") or {}
        for name, ctl in self.controllers.items():
            if isinstance(saved.get(name), dict):
                ctl.restore(saved[name])
        for knob, value in (state.get("knobs") or {}).items():
            prop = conf.REGISTRY.get(knob)
            if prop is not None and value is not None:
                prop.set(value)
        with self._lock:
            self._decisions.extend(state.get("decisions") or [])
