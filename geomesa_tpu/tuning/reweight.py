"""Plan-feedback index reweighting (docs/tuning.md, ISSUE 19 leg a).

PR 15's :class:`~geomesa_tpu.obs.accuracy.EstimateAccuracy` windows
already measure, per (type, index), how honest each index's row
estimates are — a chronically over-selecting index (estimate << rows
actually scanned) reports a large p90 error factor, and today nothing
acts on it. This module closes that loop: the planner's static
priority multiplier for a lying index is inflated by a bounded factor,
so the cost comparison in ``QueryPlanner.cost`` shifts plans toward
indexes whose estimates hold.

The factor table is HYSTERETIC by construction — three bands, not a
threshold: p90 error >= ``deadband`` engages (factor grows one step),
p90 back under the release point (halfway between honest and the
deadband) disengages (factor decays one step toward 1.0), and the
band between holds. An index oscillating across the engage boundary
therefore cannot flap plans; it parks at its current factor until the
error clearly resolves. Growth is multiplicative and clamped at
``max_adjust`` so a broken estimator can cost an index plans but never
exile it — and every step emits a decision record that the manager
ring, the ``tuning.adjust`` span, and plan explains surface.

Reads are lock-free: the factor table is an immutable dict swapped
whole (planner threads racing a pulse see either the old or the new
table, both consistent), so ``factor()`` adds zero locking to the
plan path.
"""

from __future__ import annotations


class IndexReweighter:
    """Turns EstimateAccuracy report rows into bounded, hysteretic
    planner priority factors, keyed like the accuracy window:
    ``(type_name, index_name or "full")``."""

    def __init__(
        self,
        accuracy,
        max_adjust: float = 4.0,
        deadband: float = 2.0,
        step: float = 0.5,
        min_count: int = 8,
    ):
        self.accuracy = accuracy
        self.max_adjust = float(max_adjust)
        self.deadband = float(deadband)
        self.step = float(step)
        self.min_count = int(min_count)
        # engage at p90 >= deadband; release only once p90 falls to the
        # midpoint between honest (1.0) and the deadband — the gap IS
        # the no-flap guarantee
        self.release = 1.0 + (self.deadband - 1.0) * 0.5
        self._factors: "dict[tuple[str, str], float]" = {}  # swapped whole

    def factor(self, type_name: str, index_name) -> float:
        """The planner-path read: current multiplier inflation for one
        (type, index), 1.0 when its estimates hold. Lock-free."""
        return self._factors.get((type_name, index_name or "full"), 1.0)

    def factors(self) -> "dict[tuple[str, str], float]":
        return dict(self._factors)

    def pulse(self) -> "list[dict]":
        """One control step over the current accuracy report; returns
        the decision records for every factor that moved."""
        decisions: "list[dict]" = []
        cur = dict(self._factors)
        for row in self.accuracy.report()["indexes"]:
            if row["count"] < self.min_count:
                continue  # too few samples to indict an index
            key = (row["type"], row["index"])
            old = cur.get(key, 1.0)
            p90 = row["p90_error"]
            if p90 >= self.deadband:
                new = min(self.max_adjust, old * (1.0 + self.step))
                why = (
                    f"p90 estimate error {p90:.2f}x >= {self.deadband:.2f}x: "
                    f"demote (factor {old:.2f} -> {new:.2f})"
                )
            elif p90 <= self.release and old > 1.0:
                new = max(1.0, old / (1.0 + self.step))
                why = (
                    f"p90 estimate error {p90:.2f}x recovered past "
                    f"{self.release:.2f}x: decay (factor {old:.2f} -> {new:.2f})"
                )
            else:
                continue  # hold band: hysteresis, no flapping
            if new == old:
                continue  # already at a clamp
            if new == 1.0:
                cur.pop(key, None)
            else:
                cur[key] = new
            decisions.append({
                "controller": "plan_reweight",
                "key": f"{key[0]}/{key[1]}",
                "from": round(old, 4),
                "to": round(new, 4),
                "reason": why,
            })
        if decisions:
            self._factors = cur
        return decisions

    # -- persistence (manager state file) --------------------------------
    def snapshot(self) -> "list[list]":
        return [[t, i, f] for (t, i), f in sorted(self._factors.items())]

    def restore(self, rows) -> None:
        try:
            self._factors = {
                (str(t), str(i)): float(f) for t, i, f in rows
            }
        except (TypeError, ValueError):
            self._factors = {}
