"""Knob auto-tuning: bounded hill-climb controllers (docs/tuning.md).

Each auto-tuned knob gets one :class:`ControllerSpec` — a frozen,
machine-checked declaration of WHAT is tuned (the ``conf`` knob), the
legal range (``lo``/``hi``: hard clamps, the controller can never
write outside them), the objective metric it optimizes (a name that
must exist in the metrics registry — the ``controller-registry`` lint
rule enforces it), and the step policy. The specs below are the
store's whole auto-tuned surface; adding one means adding it to
``CONTROLLERS`` in analysis/registries.py too (both directions are
lint-enforced, the same bargain as knobs and metrics).

The hill-climb itself (:class:`KnobController`) is deliberately dumb
and deliberately hysteretic: within the deadband nothing moves (a
noisy-but-healthy objective must not cause knob churn), an improving
move keeps its direction, a worsening move reverses, and a *collapsed*
objective (far below the best this controller has seen — the drifted-
workload signature) steps in the spec's declared relax direction
instead of guessing. Every proposed move is clamped, integral knobs
round, and a no-op proposal is suppressed so the decision trail only
records real changes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class ControllerSpec:
    """One auto-tuned knob's declaration. ``objective_kind`` selects
    the reading: ``counter`` (per-pulse delta of a monotonic counter),
    ``quantile`` (live histogram p99), or ``gauge`` (last set value).
    ``policy`` is ``hill`` (bounded hill-climb on the objective) or
    ``derive`` (closed-form from the objective reading — the link
    probe's ladder). ``relax_dir`` is the direction (+1/-1) to step
    when the objective collapses below its best: the spec author knows
    which way "more permissive" lies; the controller must not guess."""

    name: str
    knob: str
    lo: float
    hi: float
    objective: str
    objective_kind: str
    higher_is_better: bool
    step: float
    policy: str
    integral: bool
    relax_dir: int
    doc: str


# the store's auto-tuned surface (ISSUE 19 leg b). Bounds are chosen
# so the WORST in-range value degrades, never breaks: slot counts stay
# on the compiled ladder, row counts stay within queue/memory budgets.
CONTROLLER_SPECS: "tuple[ControllerSpec, ...]" = (
    ControllerSpec(
        name="cache_min_cost",
        knob="geomesa.cache.min.cost",
        lo=0.0,
        hi=0.05,
        objective="geomesa.cache.hit",
        objective_kind="counter",
        higher_is_better=True,
        step=0.25,
        policy="hill",
        integral=False,
        relax_dir=-1,
        doc="result-cache admission cost threshold vs cache-hit rate: "
            "when hits collapse (the workload's scans got cheaper than "
            "the frozen threshold), relax the floor so repeats cache",
    ),
    ControllerSpec(
        name="fused_chunk_slots",
        knob="geomesa.scan.fused.slots",
        lo=256.0,
        hi=2048.0,
        objective="geomesa.tuning.link.rtt",
        objective_kind="gauge",
        higher_is_better=False,
        step=0.25,
        policy="derive",
        integral=True,
        relax_dir=1,
        doc="fused transfer chunk slots derived from the measured link "
            "RTT on the doubling ladder (scan/block_kernels.py): slower "
            "links amortize more rows per round trip",
    ),
    ControllerSpec(
        name="fold_slice_rows",
        knob="geomesa.stream.fold.slice.rows",
        lo=8192.0,
        hi=262144.0,
        objective="geomesa.stream.fold.slice",
        objective_kind="quantile",
        higher_is_better=False,
        step=0.25,
        policy="hill",
        integral=True,
        relax_dir=-1,
        doc="incremental fold slice size vs slice-pause p99: smaller "
            "slices yield to queued queries sooner at the price of a "
            "longer fold window",
    ),
    ControllerSpec(
        name="flush_chunk_rows",
        knob="geomesa.stream.chunk.rows",
        lo=8192.0,
        hi=262144.0,
        objective="geomesa.stream.rows",
        objective_kind="counter",
        higher_is_better=True,
        step=0.25,
        policy="hill",
        integral=True,
        relax_dir=1,
        doc="stream flush batch rows vs flushed-row throughput: bigger "
            "batches amortize per-flush overhead until memory pressure "
            "or queue latency pushes back",
    ),
)


class KnobController:
    """Bounded hysteretic hill-climb over one spec. Stateless about
    the knob itself (the manager reads/writes ``conf``); this class
    only turns an objective reading stream into clamped proposals."""

    # hold band: relative objective movement below this is noise, not
    # signal — no move (the anti-flap half of the hysteresis)
    DEADBAND = 0.10
    # collapse: reading this far below the best ever seen means the
    # workload drifted out from under the current value — relax
    COLLAPSE = 0.5
    _EPS = 1e-9

    def __init__(self, spec: ControllerSpec):
        self.spec = spec
        self._last: Optional[float] = None
        self._best: Optional[float] = None
        self._dir = spec.relax_dir

    def _better(self, a: float, b: float) -> bool:
        return a > b if self.spec.higher_is_better else a < b

    def propose(self, current: float, reading: float) -> Optional[float]:
        """One pulse: fold in ``reading``, return the clamped next
        knob value, or None to hold. The first reading only seeds the
        baseline — a controller never moves on a single sample."""
        spec = self.spec
        if self._best is None or self._better(reading, self._best):
            self._best = reading
        last, self._last = self._last, reading
        if last is None:
            return None
        scale = max(abs(last), abs(self._best), self._EPS)
        gain = (reading - last) if spec.higher_is_better else (last - reading)
        shortfall = (
            (self._best - reading) if spec.higher_is_better
            else (reading - self._best)
        )
        collapsed = shortfall > self.COLLAPSE * scale
        if abs(gain) <= self.DEADBAND * scale and not collapsed:
            return None  # healthy and steady: hold (hysteresis)
        if collapsed:
            self._dir = spec.relax_dir
        elif gain < 0:
            self._dir = -self._dir
        nxt = current + self._dir * spec.step * (spec.hi - spec.lo)
        nxt = min(spec.hi, max(spec.lo, nxt))
        if spec.integral:
            nxt = float(int(round(nxt)))
        if nxt == current:
            return None
        return nxt

    def snapshot(self) -> dict:
        return {"last": self._last, "best": self._best, "dir": self._dir}

    def restore(self, state: dict) -> None:
        """Rehydrate from :meth:`snapshot` — how controller learning
        survives DataStore.close()/reopen instead of starting over."""
        self._last = state.get("last")
        self._best = state.get("best")
        d = state.get("dir")
        if d in (-1, 1):
            self._dir = d
