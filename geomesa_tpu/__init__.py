"""geomesa_tpu: a TPU-native spatio-temporal indexing and query framework.

A from-scratch re-design of the capabilities of GeoMesa (reference:
/root/reference, JVM/Scala) for JAX/XLA/Pallas on TPU:

- space-filling-curve indexing (Z2/Z3/XZ2/XZ3) over an HBM-resident,
  Arrow-style columnar feature table sorted by index key,
- a cost-based query planner (filter split -> strategy decision -> ranges),
- push-down filtering and aggregation (density / stats / BIN / sampling)
  executed as vectorized XLA/Pallas scans over contiguous row spans,
- multi-device scale-out via `jax.sharding.Mesh` + collective reductions
  (the analogue of GeoMesa's tablet-server fan-out + client merge).

Architecture inversion (see SURVEY.md section 7): the reference's
row-iterator-over-KV-store becomes columnar-scan-over-HBM. The planner runs
on host (thousands of ops), the scan runs on device (millions of rows).
"""

__version__ = "0.1.0"

from geomesa_tpu.sft import FeatureType, AttributeDescriptor
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection

__all__ = [
    "FeatureType",
    "AttributeDescriptor",
    "DataStore",
    "FeatureCollection",
    "__version__",
]
