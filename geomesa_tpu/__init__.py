"""geomesa_tpu: a TPU-native spatio-temporal indexing and query framework.

A from-scratch re-design of the capabilities of GeoMesa (reference:
/root/reference, JVM/Scala) for JAX/XLA/Pallas on TPU:

- space-filling-curve indexing (Z2/Z3/XZ2/XZ3) over an HBM-resident,
  Arrow-style columnar feature table sorted by index key,
- a cost-based query planner (filter split -> strategy decision -> ranges),
- push-down filtering and aggregation (density / stats / BIN / sampling)
  executed as vectorized XLA/Pallas scans over contiguous row spans,
- multi-device scale-out via `jax.sharding.Mesh` + collective reductions
  (the analogue of GeoMesa's tablet-server fan-out + client merge).

Architecture inversion (see SURVEY.md section 7): the reference's
row-iterator-over-KV-store becomes columnar-scan-over-HBM. The planner runs
on host (thousands of ops), the scan runs on device (millions of rows).
"""

__version__ = "0.1.0"

import os as _os

_cache_enabled = False


def enable_compile_cache():
    """Persistent XLA compilation cache: scan-kernel shapes are static per
    table, so every process after the first hits the disk cache instead of
    paying the 20-40 s tunnel compiles. Called lazily from the first device
    table build — NOT at import, so host-only paths never pay the jax
    import (GEOMESA_TPU_NO_COMPILE_CACHE=1 disables)."""
    global _cache_enabled
    if _cache_enabled or _os.environ.get("GEOMESA_TPU_NO_COMPILE_CACHE"):
        return
    _cache_enabled = True
    try:
        import jax

        repo_default = _os.path.join(
            _os.path.dirname(_os.path.dirname(__file__)), ".jax_cache"
        )
        if not _os.access(_os.path.dirname(repo_default), _os.W_OK):
            repo_default = _os.path.expanduser("~/.cache/geomesa_tpu/jax")
        cache = _os.environ.get("JAX_COMPILATION_CACHE_DIR", repo_default)
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # pragma: no cover - cache is best-effort
        pass


from geomesa_tpu.sft import FeatureType, AttributeDescriptor
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection

__all__ = [
    "FeatureType",
    "AttributeDescriptor",
    "DataStore",
    "FeatureCollection",
    "__version__",
]
