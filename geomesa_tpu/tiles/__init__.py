"""geomesa_tpu.tiles: the live map-tile tier (docs/tiles.md).

A slippy-map density pyramid behind the HTTP data plane: leaf tiles
aggregate rows once on an exact global leaf lattice, parents fold child
partials, and GenerationTracker's scoped invalidation keeps the whole
structure incrementally maintained under sustained ingest — the
GeoBlocks serving story (arXiv:1908.07753) this repo reproduces.

- :class:`TileLattice` — the exact tiling geometry / binning;
- :class:`TilePyramid` — precomposed grids + the from-scratch oracle;
- :func:`render` / :func:`encode_png` — deterministic stdlib PNG.
"""

from geomesa_tpu.tiles.png import KINDS, encode_png, render
from geomesa_tpu.tiles.pyramid import TileGrid, TilePyramid, TilesConfig
from geomesa_tpu.tiles.tiling import TileLattice

__all__ = [
    "TileLattice", "TilePyramid", "TilesConfig", "TileGrid",
    "KINDS", "encode_png", "render",
]
