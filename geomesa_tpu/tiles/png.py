"""Deterministic stdlib PNG encoding for tile rasters.

No imaging dependency: a PNG is a signature + IHDR + (optional PLTE) +
one zlib-compressed IDAT of filter-0 scanlines + IEND, all assembled
with ``struct`` + ``zlib``. Everything here is bit-deterministic in the
input grid — same counts in, same bytes out — which is what lets the
bench compare a served tile against its from-scratch oracle by raw byte
equality (BENCH_TILES.json ``identical``).

Renderings (one per tile kind, docs/tiles.md):

- ``count``: linear grayscale — pixel 255 is the tile's own max count;
- ``density``: log-scaled grayscale (``log1p``), the long-tail-friendly
  view the reference's DensityScan heatmaps feed;
- ``heat``: the same log scale through a fixed 256-entry black->blue->
  red->yellow->white palette (color type 3).
"""

from __future__ import annotations

import struct
import zlib

import numpy as np

#: the tile kinds the serving tier accepts
KINDS = ("density", "count", "heat")

_SIG = b"\x89PNG\r\n\x1a\n"


def _chunk(tag: bytes, data: bytes) -> bytes:
    body = tag + data
    return (
        struct.pack(">I", len(data))
        + body
        + struct.pack(">I", zlib.crc32(body) & 0xFFFFFFFF)
    )


def encode_png(img, palette=None) -> bytes:
    """PNG bytes for a ``(h, w)`` uint8 grayscale image, a ``(h, w, 3)``
    uint8 RGB image, or — with ``palette`` (a ``(n<=256, 3)`` uint8
    array) — a ``(h, w)`` uint8 index image (color type 3)."""
    a = np.ascontiguousarray(img, np.uint8)
    if palette is not None:
        if a.ndim != 2:
            raise ValueError("palette images must be 2-D index arrays")
        h, w = a.shape
        color_type = 3
    elif a.ndim == 2:
        h, w = a.shape
        color_type = 0
    elif a.ndim == 3 and a.shape[2] == 3:
        h, w = a.shape[:2]
        color_type = 2
    else:
        raise ValueError(f"unsupported image shape {a.shape}")
    ihdr = struct.pack(">IIBBBBB", w, h, 8, color_type, 0, 0, 0)
    raw = bytearray()
    for r in range(h):
        raw.append(0)  # filter type 0 per scanline
        raw += a[r].tobytes()
    out = [_SIG, _chunk(b"IHDR", ihdr)]
    if palette is not None:
        p = np.ascontiguousarray(palette, np.uint8)
        out.append(_chunk(b"PLTE", p.tobytes()))
    out.append(_chunk(b"IDAT", zlib.compress(bytes(raw), 6)))
    out.append(_chunk(b"IEND", b""))
    return b"".join(out)


def _heat_palette() -> np.ndarray:
    """Fixed 256-entry ramp: black -> blue -> red -> yellow -> white,
    piecewise-linear over four equal segments (pure integer arithmetic,
    platform-independent)."""
    p = np.zeros((256, 3), np.uint8)
    idx = np.arange(256)
    seg, t = idx // 64, (idx % 64) * 4  # t in [0, 252]
    t = np.minimum(t + (t > 0) * 3, 255)  # stretch each segment to 255
    p[seg == 0] = np.stack(
        [np.zeros(64, int), np.zeros(64, int), t[seg == 0]], axis=1
    ).astype(np.uint8)
    p[seg == 1] = np.stack(
        [t[seg == 1], np.zeros(64, int), 255 - t[seg == 1]], axis=1
    ).astype(np.uint8)
    p[seg == 2] = np.stack(
        [np.full(64, 255, int), t[seg == 2], np.zeros(64, int)], axis=1
    ).astype(np.uint8)
    p[seg == 3] = np.stack(
        [np.full(64, 255, int), np.full(64, 255, int), t[seg == 3]], axis=1
    ).astype(np.uint8)
    return p


_HEAT = _heat_palette()


def _scaled(grid: np.ndarray, log: bool) -> np.ndarray:
    g = np.asarray(grid, np.float64)
    gmax = float(g.max()) if g.size else 0.0
    if gmax <= 0.0:
        return np.zeros(g.shape, np.uint8)
    if log:
        v = np.log1p(g) * (255.0 / np.log1p(gmax))
    else:
        v = g * (255.0 / gmax)
    return np.floor(v + 0.5).astype(np.uint8)


def render(kind: str, grid) -> bytes:
    """Deterministic PNG bytes for one composed tile grid (row 0 =
    north). ``kind`` is one of :data:`KINDS`."""
    if kind == "count":
        return encode_png(_scaled(grid, log=False))
    if kind == "density":
        return encode_png(_scaled(grid, log=True))
    if kind == "heat":
        return encode_png(_scaled(grid, log=True), palette=_HEAT)
    raise ValueError(f"unknown tile kind {kind!r} (one of {KINDS})")
