"""The precomposed density pyramid: leaf scans once, parents fold.

The GeoBlocks endgame (docs/tiles.md; arXiv:1908.07753): map clients
fetch ``(z, x, y)`` tiles at thousands of requests per second while the
hot tier keeps ingesting. Rescanning rows per request loses by orders
of magnitude, so the pyramid precomposes:

- **leaf tiles** (zoom ``leaf_zoom``) aggregate rows ONCE — a single
  bbox scan binned onto the global leaf lattice
  (:class:`~geomesa_tpu.tiles.tiling.TileLattice`);
- **parents** fold their 4 children's cached grids with an exact f64
  2x2 block sum (scan/aggregations.block_sum) — never rescanning rows a
  clean child already aggregated;
- every composed grid lives in the pyramid's own
  :class:`~geomesa_tpu.cache.result.ResultCache` keyed by tile, with
  the tile's bbox as its generation key range — so a flush/fold bumping
  its mutation's key ranges (GenerationTracker's scoped invalidation)
  dirties ONLY the tiles it touched, and dirty tiles recompose lazily
  on the next fetch while far tiles keep serving warm. Single-flight
  absorbs thundering-herd fetches of the same hot tile, and the TTL
  jitter knob (``geomesa.cache.ttl.jitter``) keeps a burst of same-TTL
  tiles from re-expiring in lockstep.

Counts are integers held in f64 (exact to 2^53), and leaf binning
depends only on the point — so a pyramid tile is **bit-identical** to
:meth:`TilePyramid.fresh`, the from-scratch oracle that rescans the
tile's rows per request (also the ``mode=fresh`` server path the bench
baselines against).

Locking: ``TilePyramid._lock`` (LOCKS rank 54) guards only the delta
accounting and the leaf-scan cost EWMA — never held across a store
scan or another cache tier's lock. Cache entries ride the shared
``ResultCache._lock`` / ``GenerationTracker._lock`` discipline.

Metrics: ``geomesa.tiles.compose`` / ``.leaf.scan`` / ``.dirty``
counters here; the serving tier adds ``geomesa.tiles.fetch`` (latency
histogram), ``.served``, ``.not_modified`` and ``.fresh``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

from geomesa_tpu import fault
from geomesa_tpu.cache.generations import GenerationTracker, KeyRange
from geomesa_tpu.cache.result import ResultCache, ResultCacheConf
from geomesa_tpu.tiles.tiling import TileLattice

_EWMA_ALPHA = 0.25  # same smoothing as the tile-aggregate cost gate


@dataclass
class TilesConfig:
    """Pyramid knobs; defaults resolve from the conf.py property tier."""

    leaf_zoom: int = 3
    px: int = 256
    cache_max_bytes: int = 128 << 20
    ttl_s: Optional[float] = None
    ttl_jitter: float = 0.0
    max_age_s: float = 0.0

    @staticmethod
    def from_properties() -> "TilesConfig":
        from geomesa_tpu import conf

        return TilesConfig(
            leaf_zoom=conf.TILES_LEAF_ZOOM.get(),
            px=conf.TILES_PX.get(),
            cache_max_bytes=conf.TILES_CACHE_MAX_BYTES.get(),
            ttl_s=conf.TILES_TTL.get(),
            ttl_jitter=conf.CACHE_TTL_JITTER.get(),
            max_age_s=conf.TILES_MAX_AGE_S.get(),
        )


@dataclass(frozen=True)
class TileGrid:
    """One composed tile: the per-pixel count grid (row 0 = north) plus
    the generation tick captured at compose start — the ETag source."""

    grid: np.ndarray
    tick: int
    count: float

    @property
    def nbytes(self) -> int:
        # the ResultCache admission sizing hook (collection_nbytes)
        return int(self.grid.nbytes) + 96


class TilePyramid:
    """The tile tier over one (cold) store.

    With a :class:`~geomesa_tpu.cache.QueryCache` attached to the store,
    composed grids cache against its GenerationTracker and the pyramid
    registers for mutation-delta accounting (``cache.attach_pyramid``).
    A cacheless store still serves correct tiles — every fetch simply
    recomposes from scratch (no tracker means no safe invalidation)."""

    def __init__(self, store, config: "TilesConfig | None" = None,
                 metrics=None):
        from geomesa_tpu.lockwitness import witness
        from geomesa_tpu.metrics import resolve

        self.store = store
        self.conf = config or TilesConfig.from_properties()
        self.lattice = TileLattice(self.conf.leaf_zoom, self.conf.px)
        self.metrics = resolve(
            metrics if metrics is not None
            else getattr(store, "metrics", None)
        )
        self._lock = witness(threading.Lock(), "TilePyramid._lock")
        self._deltas = 0        # guarded-by: _lock
        self._dirty_leaves = 0  # guarded-by: _lock
        self._leaf_scan_s: dict[str, float] = {}  # guarded-by: _lock
        cache_tier = getattr(store, "cache", None)
        if cache_tier is not None:
            self.generations: GenerationTracker = cache_tier.generations
            self._result: "ResultCache | None" = ResultCache(
                ResultCacheConf(
                    max_bytes=self.conf.cache_max_bytes,
                    ttl_s=self.conf.ttl_s,
                    min_cost_s=0.0,
                    ttl_jitter=self.conf.ttl_jitter,
                ),
                self.generations,
                metrics=self.metrics,
            )
            cache_tier.attach_pyramid(self)
        else:
            self.generations = GenerationTracker()
            self._result = None

    # -- fetch paths -----------------------------------------------------
    def fetch(self, type_name: str, z: int, x: int, y: int) -> TileGrid:
        """The precomposed path: the cached grid when its generations
        are clean, else a lazy recompose (single-flight coalesced)."""
        self._check(type_name, z, x, y)
        return self._get(type_name, z, x, y)

    def fresh(self, type_name: str, z: int, x: int, y: int) -> TileGrid:
        """The from-scratch oracle (and the server's ``mode=fresh``
        baseline): one bbox scan of the tile's rows, binned on the SAME
        global leaf lattice, leaf indices shifted down to zoom ``z`` —
        bit-identical to :meth:`fetch` by construction."""
        from geomesa_tpu.scan.aggregations import tile_partial

        self._check(type_name, z, x, y)
        tick = self.generations.tick()
        col, row, c0, r0 = self._tile_rows(type_name, z, x, y)
        shift = self.lattice.leaf_zoom - z
        grid = tile_partial(
            (col - c0) >> shift, (row - r0) >> shift,
            self.conf.px, self.conf.px,
        )
        self.metrics.counter("geomesa.tiles.fresh")
        return TileGrid(grid=grid, tick=tick, count=float(grid.sum()))

    def peek(self, type_name: str, z: int, x: int, y: int) -> Optional[TileGrid]:
        """The still-valid cached grid, or None — the conditional-GET
        check (a matching ETag answers 304 with no compose or render
        work). Read-only: no counters, no entry drops."""
        if self._result is None or not self.lattice.valid(z, x, y):
            return None
        return self._result.peek(self._key(type_name, z, x, y))

    # -- mutation hooks --------------------------------------------------
    def note_delta(self, type_name: str, bounds=None) -> int:
        """One mutated batch landed over ``bounds`` (the QueryCache
        forwards every on_mutation): account how many leaf tiles its
        key range can dirty. Invalidation itself rides the shared
        GenerationTracker — entries re-validate lazily on fetch."""
        n = self.lattice.leaf_tiles_overlapping(bounds)
        with self._lock:
            self._deltas += 1
            self._dirty_leaves += n
        self.metrics.counter("geomesa.tiles.dirty", n)
        return n

    def invalidate_type(self, type_name: str) -> int:
        """Drop every cached grid for one type (schema dropped)."""
        if self._result is None:
            return 0
        return self._result.invalidate_type(type_name)

    def sweep(self, type_name: "str | None" = None) -> int:
        """Eagerly drop stale/expired grids (quarantine hook)."""
        if self._result is None:
            return 0
        return self._result.sweep(type_name)

    def stats(self) -> dict:
        with self._lock:
            deltas, dirty = self._deltas, self._dirty_leaves
        return {
            "tile_grid_entries": len(self._result) if self._result else 0,
            "tile_grid_bytes": (
                self._result.bytes_resident if self._result else 0
            ),
            "tile_deltas": deltas,
            "tile_dirty_leaves": dirty,
            "leaf_zoom": self.lattice.leaf_zoom,
            "px": self.conf.px,
        }

    # -- composition -----------------------------------------------------
    def _check(self, type_name: str, z: int, x: int, y: int) -> None:
        self.store.get_schema(type_name)  # KeyError -> the caller's 404
        if not self.lattice.valid(z, x, y):
            cx, cy = self.lattice.n_tiles(max(min(z, self.lattice.leaf_zoom), 0))
            raise ValueError(
                f"tile ({z}/{x}/{y}) outside the pyramid: zoom in "
                f"[0, {self.lattice.leaf_zoom}], {cx}x{cy} tiles at that zoom"
            )

    def _key(self, type_name: str, z: int, x: int, y: int) -> str:
        return f"tiles/{type_name}/{z}/{x}/{y}"

    def _get(self, type_name: str, z: int, x: int, y: int) -> TileGrid:
        if self._result is None:
            return self._compose(type_name, z, x, y)[0]
        key_range = KeyRange(
            boxes=(self.lattice.tile_bbox(z, x, y),), interval=None
        )
        value, _status, _probe = self._result.get_or_compute(
            self._key(type_name, z, x, y), type_name, key_range,
            lambda: self._compose(type_name, z, x, y),
        )
        return value

    def _compose(self, type_name: str, z: int, x: int, y: int):
        """Build one grid: a leaf scan at ``leaf_zoom``, else an exact
        2x2 block-sum fold of the 4 children (each fetched through the
        cache, so clean subtrees are never rescanned). Returns
        ``(TileGrid, cost_seconds)`` — the ResultCache compute shape."""
        from geomesa_tpu.scan.aggregations import block_sum

        t0 = time.perf_counter()
        fault.fault_point("tiles.compose")
        tick = self.generations.tick()
        px = self.lattice.px
        if z >= self.lattice.leaf_zoom:
            grid = self._leaf_grid(type_name, z, x, y)
        else:
            combined = np.zeros((2 * px, 2 * px), np.float64)
            for cz, cx, cy in self.lattice.children_of(z, x, y):
                dx, dy = cx - 2 * x, cy - 2 * y
                child = self._get(type_name, cz, cx, cy)
                combined[
                    dy * px:(dy + 1) * px, dx * px:(dx + 1) * px
                ] = child.grid
            grid = block_sum(combined, 2)
        self.metrics.counter("geomesa.tiles.compose")
        g = TileGrid(grid=grid, tick=tick, count=float(grid.sum()))
        return g, time.perf_counter() - t0

    def _leaf_grid(self, type_name: str, z: int, x: int, y: int) -> np.ndarray:
        from geomesa_tpu.scan.aggregations import tile_partial

        fault.fault_point("tiles.leaf.scan")
        t0 = time.perf_counter()
        col, row, c0, r0 = self._tile_rows(type_name, z, x, y)
        grid = tile_partial(col - c0, row - r0, self.conf.px, self.conf.px)
        scan_s = time.perf_counter() - t0
        with self._lock:
            prev = self._leaf_scan_s.get(type_name)
            self._leaf_scan_s[type_name] = (
                scan_s if prev is None
                else prev + _EWMA_ALPHA * (scan_s - prev)
            )
        self.metrics.counter("geomesa.tiles.leaf.scan")
        return grid

    def _tile_rows(self, type_name: str, z: int, x: int, y: int):
        """One closed-bbox scan of a tile's rows, binned on the global
        leaf lattice and masked to the tile's half-open leaf-pixel span
        (a boundary row the closed scan returned for BOTH neighbors
        bins into exactly one). Returns (col, row, col0, row0) with the
        mask applied."""
        from geomesa_tpu.filter.predicates import BBox
        from geomesa_tpu.planning.hints import QueryHints

        sft = self.store.get_schema(type_name)
        bbox = self.lattice.tile_bbox(z, x, y)
        rows = self.store.query(
            type_name, BBox(sft.geom_field, *bbox),
            hints=QueryHints(cache="bypass"),
        )
        if len(rows):
            px_, py_ = rows.representative_xy()
            col, row, ok = self.lattice.bin_leaf(px_, py_)
        else:
            col = row = np.zeros(0, np.int64)
            ok = np.zeros(0, bool)
        c0, c1, r0, r1 = self.lattice.leaf_span(z, x, y)
        keep = ok & (col >= c0) & (col < c1) & (row >= r0) & (row < r1)
        return col[keep], row[keep], c0, r0
