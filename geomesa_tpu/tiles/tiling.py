"""The slippy-tile lattice: exact global leaf-pixel binning.

The pyramid's exactness story (docs/tiles.md) lives here. A map tile at
zoom ``z`` is one cell of a plain-EPSG:4326 WorldCRS84Quad-style grid —
``2^(z+1) x 2^z`` tiles, each rendered as a ``px x px`` raster. The
pyramid's FINEST zoom is ``leaf_zoom``; every zoom above it derives from
leaf partials, never from its own scan.

**The global leaf lattice.** All binning happens ONCE, at leaf raster
resolution: the world splits into ``2^(leaf_zoom+1)*px`` columns by
``2^leaf_zoom*px`` rows of leaf pixels, with exact binary-rational edge
arrays (``k * 360/2^n`` sums exactly in f64 — the TileAggregateCache
edge discipline, cache/tiles.py). A point's leaf pixel depends only on
the point, not on which tile asked: half-open ``[edge_k, edge_{k+1})``
membership via searchsorted, so adjacent tiles can never double-count a
boundary row and any zoom-``z`` pixel is an EXACT f64 integer sum of the
leaf pixels it covers — which is what makes a recomposed parent
bit-identical to a from-scratch aggregation of the same rows.

Row index convention: tile ``y`` and raster rows count from the NORTH
edge (the slippy convention PNG scanlines want); the ascending latitude
edge array is south-up, so :meth:`TileLattice.bin_leaf` flips once.
"""

from __future__ import annotations

import numpy as np


class TileLattice:
    """The fixed tiling geometry for one pyramid: leaf zoom + tile px."""

    def __init__(self, leaf_zoom: int = 3, px: int = 256):
        if leaf_zoom < 0:
            raise ValueError(f"leaf_zoom must be >= 0, got {leaf_zoom}")
        if px < 1:
            raise ValueError(f"px must be >= 1, got {px}")
        self.leaf_zoom = int(leaf_zoom)
        self.px = int(px)
        #: global leaf-pixel grid dimensions
        self.nx = (1 << (self.leaf_zoom + 1)) * self.px
        self.ny = (1 << self.leaf_zoom) * self.px
        # exact binary-rational pixel edges (see module docstring): the
        # ONE pair of arrays every binning and bbox derivation reads
        self.xe = -180.0 + np.arange(self.nx + 1) * (360.0 / self.nx)
        self.ye = -90.0 + np.arange(self.ny + 1) * (180.0 / self.ny)

    def n_tiles(self, z: int) -> tuple[int, int]:
        """(columns, rows) of the zoom-``z`` tile grid."""
        return 1 << (z + 1), 1 << z

    def valid(self, z: int, x: int, y: int) -> bool:
        if not 0 <= z <= self.leaf_zoom:
            return False
        cx, cy = self.n_tiles(z)
        return 0 <= x < cx and 0 <= y < cy

    def leaf_span(self, z: int, x: int, y: int) -> tuple[int, int, int, int]:
        """Half-open leaf-pixel span ``(col0, col1, row0, row1)`` of one
        tile; rows count from the north edge."""
        s = self.px << (self.leaf_zoom - z)
        return x * s, (x + 1) * s, y * s, (y + 1) * s

    def tile_bbox(self, z: int, x: int, y: int) -> tuple[float, float, float, float]:
        """(xmin, ymin, xmax, ymax) of one tile — read off the exact
        edge arrays, so a closed bbox scan of it covers exactly the
        rows that can bin inside (boundary rows bin to ONE neighbor)."""
        c0, c1, r0, r1 = self.leaf_span(z, x, y)
        return (
            float(self.xe[c0]), float(self.ye[self.ny - r1]),
            float(self.xe[c1]), float(self.ye[self.ny - r0]),
        )

    def bin_leaf(self, x, y) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Global leaf pixel ``(col, north_row)`` per point plus the
        in-world mask. Half-open membership; the world's own closed
        upper edges (lon=180, lat=90) join the last pixel, so every
        in-world point bins exactly once."""
        x = np.asarray(x, np.float64)
        y = np.asarray(y, np.float64)
        ok = (x >= -180.0) & (x <= 180.0) & (y >= -90.0) & (y <= 90.0)
        col = np.searchsorted(self.xe, x, side="right") - 1
        row_s = np.searchsorted(self.ye, y, side="right") - 1
        col = np.clip(col, 0, self.nx - 1)
        row_s = np.clip(row_s, 0, self.ny - 1)
        return col, (self.ny - 1) - row_s, ok

    def leaf_tiles_overlapping(self, bounds=None) -> int:
        """How many LEAF tiles a mutation over ``bounds`` (xmin, ymin,
        xmax, ymax; None = everywhere) can dirty — the delta-to-tile-
        range accounting behind the ``geomesa.tiles.dirty`` metric."""
        cx, cy = self.n_tiles(self.leaf_zoom)
        if bounds is None:
            return cx * cy
        x0, y0, x1, y1 = (float(v) for v in bounds)
        x0, x1 = max(x0, -180.0), min(x1, 180.0)
        y0, y1 = max(y0, -90.0), min(y1, 90.0)
        if x1 < x0 or y1 < y0:
            return 0
        col, row, _ = self.bin_leaf(
            np.array([x0, x1]), np.array([y0, y1])
        )
        i0, i1 = int(col[0]) // self.px, int(col[1]) // self.px
        # y1 is the NORTH edge of the delta -> the smaller north row
        j0, j1 = int(row[1]) // self.px, int(row[0]) // self.px
        return (i1 - i0 + 1) * (j1 - j0 + 1)

    def children_of(self, z: int, x: int, y: int):
        """The 4 children of one tile at zoom ``z+1``, north-west first
        in raster order: (dx, dy) over {0,1} x {0,1}."""
        return [
            (z + 1, 2 * x + dx, 2 * y + dy)
            for dy in (0, 1) for dx in (0, 1)
        ]
