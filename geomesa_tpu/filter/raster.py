"""Raster-interval polygon approximations (arXiv 2307.01716).

A query/join polygon rasterizes ONCE onto a Z2-aligned grid — the cells
are genuine Z2 SFC cells at one level ``g`` (the finest whose bbox window
fits the ``geomesa.raster.max.cells`` budget), so every cell is both an
axis-aligned rectangle in (lon, lat) AND a contiguous z-code range. Each
cell classifies conservatively (geometry.classify_raster_cells) as

- FULL    — entirely inside the polygon, with margin: any point within
            the cell is a guaranteed f64 hit;
- OUT     — entirely outside, with margin: a guaranteed miss;
- PARTIAL — the boundary residue, where the exact even-odd PIP still runs.

Two products feed the scan engine:

1. :meth:`RasterApprox.zranges` — the polygon's covering z-ranges derived
   from the raster itself: FULL cells emit *contained* ranges (their rows
   are certain hits — no kernel work, no refinement; the round-3
   contained-span machinery applies unchanged, now valid for polygons
   because full-cell containment implies membership), PARTIAL cells emit
   overlap ranges, OUT cells inside the bbox emit nothing (pruned before
   any device work — the win the plain bbox decomposition cannot see).
2. :meth:`RasterApprox.pack_block` — the packed [1 + R, 128] f32 interval
   stack the scan kernel classifies candidate rows against (sorted
   integer intervals over row-major bbox-local cell ids; see
   block_kernels._raster_classify): full -> wide+inner, out -> neither,
   partial -> the exact PIP leg (device residue) or host refinement.

The host-side :meth:`classify_points` powers the adaptive spatial join
(sql/join.py): definite-in/definite-out points skip the exact predicate,
only boundary-cell points pay it.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass

import numpy as np

from geomesa_tpu import geometry as geo
from geomesa_tpu.curve.zorder import Z2

# conservative classification margin, degrees: must exceed the stored-f32
# coordinate rounding (ulp(360) ~ 3e-5) plus the kernel's f32 cell
# arithmetic error (~6e-5 worst case), so a point the KERNEL lands in a
# full/out cell is truly within margin of that cell at f64. 3e-4 keeps
# ~5x headroom; cells must be >= ~8 margins wide to classify usefully, so
# polygons smaller than ~2.4e-3 deg skip rasterization (build() -> None).
RASTER_MARGIN = 3e-4

Z2_BITS = 31  # ordinal bits per dimension (curve.z2sfc.Z2SFC precision)


@dataclass
class RasterApprox:
    """One polygon's Z2-aligned raster: cell classes + interval forms."""

    level: int          # z2 grid level g (2^g cells per dimension)
    i0: int             # window origin, level-g cell ordinals
    j0: int
    classes: np.ndarray  # int8 [ny, nx] (geometry.RASTER_* codes)
    x0: float           # window origin in degrees (exact cell edges)
    y0: float
    cell_w: float       # cell size, degrees (exact binary rationals)
    cell_h: float
    # row-major interval runs over c = j * nx + i (non-OUT cells only),
    # inclusive [lo, hi] with a full/partial flag per run
    ilo: np.ndarray = None
    ihi: np.ndarray = None
    ifull: np.ndarray = None

    def __post_init__(self):
        flat = self.classes.ravel()
        runs = np.flatnonzero(np.diff(flat)) + 1
        starts = np.concatenate([[0], runs])
        ends = np.concatenate([runs, [len(flat)]])
        keep = flat[starts] != geo.RASTER_OUT
        self.ilo = starts[keep].astype(np.int64)
        self.ihi = (ends[keep] - 1).astype(np.int64)
        self.ifull = flat[starts[keep]] == geo.RASTER_FULL

    # -- shape accessors --------------------------------------------------
    @property
    def ny(self) -> int:
        return self.classes.shape[0]

    @property
    def nx(self) -> int:
        return self.classes.shape[1]

    @property
    def n_cells(self) -> int:
        return self.classes.size

    @property
    def cell_counts(self) -> tuple[int, int, int]:
        """(full, partial, out) cell counts — the selectivity signal the
        adaptive join planner reads."""
        full = int((self.classes == geo.RASTER_FULL).sum())
        part = int((self.classes == geo.RASTER_PARTIAL).sum())
        return full, part, self.n_cells - full - part

    @property
    def boundary_fraction(self) -> float:
        """Partial cells / non-out cells: the fraction of covered area
        that still pays the exact predicate."""
        full, part, _ = self.cell_counts
        return part / max(full + part, 1)

    @property
    def decided_fraction(self) -> float:
        """(full + out) / all cells: how much of the bbox the raster
        resolves without the exact predicate. The worthwhile-ness gate."""
        full, part, out = self.cell_counts
        return (full + out) / max(self.n_cells, 1)

    # -- host classification ----------------------------------------------
    def classify_points(self, x, y) -> np.ndarray:
        """int8 [n] cell class per point (RASTER_OUT for points outside
        the grid window — the window covers the polygon bbox, so such
        points are guaranteed misses)."""
        x = np.asarray(x, dtype=np.float64)
        y = np.asarray(y, dtype=np.float64)
        i = np.floor((x - self.x0) / self.cell_w).astype(np.int64)
        j = np.floor((y - self.y0) / self.cell_h).astype(np.int64)
        ok = (i >= 0) & (i < self.nx) & (j >= 0) & (j < self.ny)
        out = np.zeros(len(x), dtype=np.int8)
        out[ok] = self.classes[j[ok], i[ok]]
        return out

    # -- z-range emission -------------------------------------------------
    def zranges(self, max_ranges: int | None = None):
        """(lo [u64], hi [u64], contained [bool]) covering z-ranges of the
        non-OUT cells at this raster's level: consecutive-morton runs of
        one class merge; past ``max_ranges`` the closest-gap neighbours
        coalesce as *overlap* ranges (absorbed OUT/FULL cells downgrade to
        kernel-classified rows — a superset, never wrong)."""
        jj, ii = np.nonzero(self.classes != geo.RASTER_OUT)
        if len(jj) == 0:
            z = np.zeros(0, np.uint64)
            return z, z.copy(), np.zeros(0, bool)
        gi = (ii + self.i0).astype(np.uint64)
        gj = (jj + self.j0).astype(np.uint64)
        m = np.asarray(Z2.index(gi, gj))
        full = self.classes[jj, ii] == geo.RASTER_FULL
        order = np.argsort(m)
        m, full = m[order], full[order]
        brk = np.flatnonzero((np.diff(m) != 1) | (full[1:] != full[:-1]))
        starts = np.concatenate([[0], brk + 1])
        ends = np.concatenate([brk, [len(m) - 1]])
        shift = np.uint64(2 * (Z2_BITS - self.level))
        lo = m[starts] << shift
        hi = ((m[ends] + np.uint64(1)) << shift) - np.uint64(1)
        contained = full[starts]
        if max_ranges is not None and len(lo) > max_ranges:
            lo, hi, contained = _coalesce_ranges(lo, hi, contained, max_ranges)
        return lo, hi, contained

    # -- kernel interval stack --------------------------------------------
    def pack_block(self, bucket: int) -> np.ndarray:
        """[1 + bucket, 128] f32 kernel block (block_kernels raster leg).

        Row 0 header lanes: (x0, y0, 1/cell_w, 1/cell_h, nx, ny). Rows
        1..bucket: one interval each, lanes (lo, hi, cls) with cls +1 =
        full / -1 = partial; pad rows carry lo=1 > hi=0 (never match).
        Cell ids fit f32 exactly (max.cells <= 2^24). More runs than the
        bucket coalesce via consecutive-run grouping: a merged group is
        full only if it was one contiguous all-full stretch, else partial
        (absorbed out-gap rows become boundary residue — safe)."""
        lo, hi, full = self.ilo, self.ihi, self.ifull
        if len(lo) > bucket:
            groups = np.array_split(np.arange(len(lo)), bucket)
            lo = np.array([lo[g[0]] for g in groups])
            nhi = np.array([self.ihi[g[-1]] for g in groups])
            nfull = np.array([
                bool(self.ifull[g].all())
                and bool((self.ilo[g][1:] == self.ihi[g][:-1] + 1).all())
                for g in groups
            ])
            hi, full = nhi, nfull
        from geomesa_tpu.scan.block_kernels import LANES

        out = np.zeros((1 + bucket, LANES), np.float32)
        out[0, 0] = self.x0
        out[0, 1] = self.y0
        out[0, 2] = 1.0 / self.cell_w
        out[0, 3] = 1.0 / self.cell_h
        out[0, 4] = self.nx
        out[0, 5] = self.ny
        out[1:, 0] = 1.0
        out[1:, 1] = 0.0
        n = len(lo)
        out[1 : 1 + n, 0] = lo
        out[1 : 1 + n, 1] = hi
        out[1 : 1 + n, 2] = np.where(full, 1.0, -1.0)
        return out


def _coalesce_ranges(lo, hi, contained, max_ranges):
    """Merge closest-gap neighbours until <= max_ranges. A merge spanning
    a gap (or mixing classes) is an overlap range: the raster kernel leg /
    host refinement re-excludes the absorbed rows exactly."""
    lo = lo.astype(np.uint64)
    hi = hi.astype(np.uint64)
    contained = contained.copy()
    while len(lo) > max_ranges:
        gaps = (lo[1:] - hi[:-1]).astype(np.int64)
        k = len(lo) - max_ranges
        merge = np.argsort(gaps, kind="stable")[:k]
        drop = np.zeros(len(lo), bool)
        new_cont = contained.copy()
        for i in sorted(merge.tolist(), reverse=True):
            if drop[i + 1]:
                continue  # chained merges resolve next pass
            hi[i] = max(hi[i], hi[i + 1])
            new_cont[i] = bool(
                contained[i] and contained[i + 1] and gaps[i] == 1
            )
            drop[i + 1] = True
        keep = ~drop
        lo, hi, contained = lo[keep], hi[keep], new_cont[keep]
    return lo, hi, contained


def build_raster(
    geom: "geo.Polygon | geo.MultiPolygon",
    max_cells: int | None = None,
    margin: float = RASTER_MARGIN,
    min_decided: float = 0.25,
) -> "RasterApprox | None":
    """Rasterize one polygon onto the finest Z2-aligned grid whose bbox
    window fits ``max_cells``, or None when rasterization cannot help:
    non-polygon input, a polygon too small for margin-safe cells, or a
    raster that decides less than ``min_decided`` of its bbox (slivers —
    everything would be boundary residue anyway)."""
    if not isinstance(geom, (geo.Polygon, geo.MultiPolygon)):
        return None
    from geomesa_tpu.conf import RASTER_MAX_CELLS

    if max_cells is None:
        max_cells = RASTER_MAX_CELLS.get()
    bx0, by0, bx1, by1 = geom.bounds()
    bx0, by0 = max(bx0, -180.0), max(by0, -90.0)
    bx1, by1 = min(bx1, 180.0), min(by1, 90.0)
    if bx1 < bx0 or by1 < by0:
        return None
    for level in range(Z2_BITS, 0, -1):
        cw = 360.0 / (1 << level)
        ch = 180.0 / (1 << level)
        if cw < 8 * margin or ch < 8 * margin:
            continue  # cells too small to classify past the margin
        i0 = min(int((bx0 + 180.0) / cw), (1 << level) - 1)
        i1 = min(int((bx1 + 180.0) / cw), (1 << level) - 1)
        j0 = min(int((by0 + 90.0) / ch), (1 << level) - 1)
        j1 = min(int((by1 + 90.0) / ch), (1 << level) - 1)
        nx, ny = i1 - i0 + 1, j1 - j0 + 1
        if nx * ny <= max_cells:
            break
    else:
        return None
    x_edges = -180.0 + (i0 + np.arange(nx + 1)) * cw
    y_edges = -90.0 + (j0 + np.arange(ny + 1)) * ch
    classes = geo.classify_raster_cells(geom, x_edges, y_edges, margin)
    approx = RasterApprox(
        level=level, i0=i0, j0=j0, classes=classes,
        x0=float(x_edges[0]), y0=float(y_edges[0]), cell_w=cw, cell_h=ch,
    )
    if approx.decided_fraction < min_decided:
        return None
    return approx


# -- memoized build (joins re-probe the same polygons; the planner's
# scan-config memo covers the query path, this covers direct callers) -----

_CACHE: "OrderedDict[tuple, RasterApprox | None]" = OrderedDict()
_CACHE_LOCK = threading.Lock()
_CACHE_MAX = 256


def clear_cache() -> None:
    """Drop memoized rasters (tests toggling the geomesa.raster.* knobs
    mid-process must not serve a stale build)."""
    with _CACHE_LOCK:
        _CACHE.clear()


def raster_for(
    geom, max_cells: int | None = None, min_edges: int | None = None
) -> "RasterApprox | None":
    """LRU-memoized :func:`build_raster`, gated by the config knobs:
    returns None when rasterization is disabled, the polygon is below
    ``geomesa.raster.min.edges``, or build_raster declines."""
    from geomesa_tpu.conf import RASTER_ENABLED, RASTER_MIN_EDGES

    if not RASTER_ENABLED.get():
        return None
    if not isinstance(geom, (geo.Polygon, geo.MultiPolygon)):
        return None
    if min_edges is None:
        min_edges = RASTER_MIN_EDGES.get()
    n_edges = sum(len(r) - 1 for r in geo._rings_of(geom))
    if n_edges < min_edges:
        return None
    key = (geom.wkt, max_cells)
    with _CACHE_LOCK:
        if key in _CACHE:
            _CACHE.move_to_end(key)
            return _CACHE[key]
    approx = build_raster(geom, max_cells=max_cells)
    with _CACHE_LOCK:
        _CACHE[key] = approx
        while len(_CACHE) > _CACHE_MAX:
            _CACHE.popitem(last=False)
    return approx
