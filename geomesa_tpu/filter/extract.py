"""Decomposition of filter trees into indexable values.

The planner equivalent of the reference's FilterHelper
(/root/reference/geomesa-filter/src/main/scala/org/locationtech/geomesa/
filter/FilterHelper.scala:100-130 `extractGeometries`/`extractIntervals`)
and the FilterValues algebra (filter/FilterValues.scala): walk the tree,
pull out the spatial / temporal constraints on a property, combining AND by
intersection and OR by union, and report whether the extraction is *exact*
(the predicate is fully answered by the extracted values) or needs the full
filter re-applied after the index scan (`useFullFilter`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Generic, Sequence, TypeVar

import numpy as np

from geomesa_tpu import geometry as geo
from geomesa_tpu.filter.predicates import (
    And,
    BBox,
    Between,
    Cmp,
    Contains,
    During,
    DWithin,
    Exclude,
    Filter,
    IdFilter,
    In,
    Include,
    Intersects,
    Not,
    Or,
    Within,
)

T = TypeVar("T")

# epoch-millis bounds used for one-sided temporal predicates
MIN_MS = 0
MAX_MS = np.iinfo(np.int64).max // 2


@dataclass
class FilterValues(Generic[T]):
    """Extracted values plus exactness flags (reference FilterValues).

    - ``values``: the extracted constraints (geometries or intervals); their
      union covers everything the filter can match on this property.
    - ``precise``: the values exactly express the filter's constraint on the
      property (no residual filtering needed for it).
    - ``disjoint``: the filter is unsatisfiable on this property (e.g. an
      AND of non-overlapping boxes) — the query can return empty.
    """

    values: list = field(default_factory=list)
    precise: bool = True
    disjoint: bool = False

    @property
    def empty(self) -> bool:
        return not self.values and not self.disjoint

    @staticmethod
    def nothing() -> "FilterValues":
        return FilterValues(values=[], precise=True)

    @staticmethod
    def disjoint_() -> "FilterValues":
        return FilterValues(values=[], disjoint=True)


def _references_prop(f: Filter, prop: str) -> bool:
    """Does any predicate in the tree constrain ``prop``?"""
    if isinstance(f, (And, Or)):
        return any(_references_prop(c, prop) for c in f.filters)
    if isinstance(f, Not):
        return _references_prop(f.filter, prop)
    return getattr(f, "prop", None) == prop


def _imprecise_children(parts, children, prop) -> bool:
    """True when some child contributed no extractable values but still
    constrains the property (e.g. a NOT branch): the combined values are
    then a superset, not exact."""
    return any(
        not p.values and not p.disjoint and _references_prop(c, prop)
        for p, c in zip(parts, children)
    )


# ---------------------------------------------------------------------------
# geometry extraction
# ---------------------------------------------------------------------------


def _predicate_geometry(f: Filter, prop: str):
    """(geometry, precise) for a single spatial predicate on prop, else None."""
    if isinstance(f, BBox) and f.prop == prop:
        return geo.box(f.xmin, f.ymin, f.xmax, f.ymax), True
    if isinstance(f, (Intersects, Within)) and f.prop == prop:
        return f.geom, True
    if isinstance(f, Contains) and f.prop == prop:
        # feature contains query geom -> feature's extent must overlap it;
        # ranges from the geom's bounds are a superset, not exact
        return f.geom, False
    if isinstance(f, DWithin) and f.prop == prop:
        return geo.box(*f.bounds), False
    return None


def extract_geometries(f: Filter, prop: str) -> FilterValues:
    """Geometries constraining ``prop``, unioned across ORs, intersected
    (by bbox) across ANDs. Reference FilterHelper.extractGeometries."""
    if isinstance(f, (Include, Exclude, IdFilter)):
        return FilterValues.nothing()
    single = _predicate_geometry(f, prop)
    if single is not None:
        g, precise = single
        return FilterValues(values=[g], precise=precise)
    if isinstance(f, And):
        all_parts = [extract_geometries(c, prop) for c in f.filters]
        if any(p.disjoint for p in all_parts):
            return FilterValues.disjoint_()
        # a child constraining prop without extractable values (e.g. NOT)
        # makes the extraction a superset, not exact
        imprecise = _imprecise_children(all_parts, f.filters, prop)
        parts = [p for p in all_parts if p.values]
        if not parts:
            return FilterValues.nothing()
        # AND of spatial constraints: intersect via bbox intersection; keep
        # the exact geometry when one side is a covering box of the other
        out = parts[0]
        for p in parts[1:]:
            out = _intersect_geom_values(out, p)
            if out.disjoint:
                return out
        if imprecise:
            out = FilterValues(values=out.values, precise=False)
        return out
    if isinstance(f, Or):
        parts = [extract_geometries(c, prop) for c in f.filters]
        if any(p.empty for p in parts):
            # some branch is unconstrained on prop -> no usable extraction
            return FilterValues.nothing()
        vals: list = []
        precise = True
        for p in parts:
            if p.disjoint:
                continue
            vals.extend(p.values)
            precise &= p.precise
        return FilterValues(values=vals, precise=precise)
    if isinstance(f, Not):
        return FilterValues.nothing()
    return FilterValues.nothing()


def _intersect_geom_values(a: FilterValues, b: FilterValues) -> FilterValues:
    out: list = []
    precise = a.precise and b.precise
    for ga in a.values:
        for gb in b.values:
            ba, bb = np.array(ga.bounds()), np.array(gb.bounds())
            if not bool(geo.bbox_intersects(ba, bb)):
                continue
            inter = (
                max(ba[0], bb[0]),
                max(ba[1], bb[1]),
                min(ba[2], bb[2]),
                min(ba[3], bb[3]),
            )
            # keep the non-box geometry when the other is its covering box
            if _is_box(ga) and not _is_box(gb):
                out.append(gb if _box_covers(ba, bb) else geo.box(*inter))
                precise &= _box_covers(ba, bb)
            elif _is_box(gb) and not _is_box(ga):
                out.append(ga if _box_covers(bb, ba) else geo.box(*inter))
                precise &= _box_covers(bb, ba)
            else:
                out.append(geo.box(*inter))
                precise &= _is_box(ga) and _is_box(gb)
    if not out:
        return FilterValues.disjoint_()
    return FilterValues(values=out, precise=precise)


def _is_box(g: geo.Geometry) -> bool:
    if not isinstance(g, geo.Polygon) or g.holes:
        return False
    ring = g.shell
    if len(ring) != 5:
        return False
    xs, ys = set(ring[:, 0].tolist()), set(ring[:, 1].tolist())
    return len(xs) == 2 and len(ys) == 2


def _box_covers(outer: np.ndarray, inner: np.ndarray) -> bool:
    return bool(
        outer[0] <= inner[0]
        and outer[1] <= inner[1]
        and outer[2] >= inner[2]
        and outer[3] >= inner[3]
    )


def geometry_bounds(fv: FilterValues) -> list[tuple[float, float, float, float]]:
    """Bounding boxes of extracted geometries, clipped to the world."""
    out = []
    for g in fv.values:
        x0, y0, x1, y1 = g.bounds()
        out.append(
            (max(x0, -180.0), max(y0, -90.0), min(x1, 180.0), min(y1, 90.0))
        )
    return out


# ---------------------------------------------------------------------------
# interval extraction
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Interval:
    """[lo, hi) epoch millis."""

    lo: int
    hi: int

    def intersect(self, other: "Interval") -> "Interval | None":
        lo, hi = max(self.lo, other.lo), min(self.hi, other.hi)
        return Interval(lo, hi) if lo < hi else None


def _predicate_interval(f: Filter, prop: str):
    if isinstance(f, During) and f.prop == prop:
        return Interval(f.lo_ms, f.hi_ms), True
    if isinstance(f, Between) and f.prop == prop and _is_ms(f.lo) and _is_ms(f.hi):
        return Interval(int(f.lo), int(f.hi) + 1), True  # BETWEEN is inclusive
    if isinstance(f, Cmp) and f.prop == prop and _is_ms(f.value):
        v = int(f.value)
        if f.op == "<":
            return Interval(MIN_MS, v), True
        if f.op == "<=":
            return Interval(MIN_MS, v + 1), True
        if f.op == ">":
            return Interval(v + 1, MAX_MS), True
        if f.op == ">=":
            return Interval(v, MAX_MS), True
        if f.op == "=":
            return Interval(v, v + 1), True
    return None


def _is_ms(v) -> bool:
    return isinstance(v, (int, np.integer)) and not isinstance(v, bool)


def extract_intervals(f: Filter, prop: str) -> FilterValues:
    """Time intervals constraining ``prop``. Reference extractIntervals."""
    if isinstance(f, (Include, Exclude, IdFilter)):
        return FilterValues.nothing()
    single = _predicate_interval(f, prop)
    if single is not None:
        iv, precise = single
        if iv.lo >= iv.hi:
            return FilterValues.disjoint_()
        return FilterValues(values=[iv], precise=precise)
    if isinstance(f, And):
        all_parts = [extract_intervals(c, prop) for c in f.filters]
        if any(p.disjoint for p in all_parts):
            return FilterValues.disjoint_()
        imprecise = _imprecise_children(all_parts, f.filters, prop)
        parts = [p for p in all_parts if p.values]
        if not parts:
            return FilterValues.nothing()
        out = parts[0]
        for p in parts[1:]:
            merged = []
            for a in out.values:
                for b in p.values:
                    iv = a.intersect(b)
                    if iv:
                        merged.append(iv)
            if not merged:
                return FilterValues.disjoint_()
            out = FilterValues(values=merged, precise=out.precise and p.precise)
        if imprecise:
            out = FilterValues(values=out.values, precise=False)
        return out
    if isinstance(f, Or):
        parts = [extract_intervals(c, prop) for c in f.filters]
        if any(p.empty for p in parts):
            return FilterValues.nothing()
        vals: list = []
        precise = True
        for p in parts:
            if p.disjoint:
                continue
            vals.extend(p.values)
            precise &= p.precise
        return FilterValues(values=_merge_intervals(vals), precise=precise)
    return FilterValues.nothing()


def _merge_intervals(ivs: Sequence[Interval]) -> list[Interval]:
    if not ivs:
        return []
    ivs = sorted(ivs, key=lambda i: (i.lo, i.hi))
    out = [ivs[0]]
    for iv in ivs[1:]:
        if iv.lo <= out[-1].hi:
            out[-1] = Interval(out[-1].lo, max(out[-1].hi, iv.hi))
        else:
            out.append(iv)
    return out


# ---------------------------------------------------------------------------
# id extraction
# ---------------------------------------------------------------------------


def extract_ids(f: Filter) -> FilterValues:
    """Feature ids from IdFilter terms (AND intersects, OR unions)."""
    if isinstance(f, IdFilter):
        return FilterValues(values=sorted(set(f.ids)), precise=True)
    if isinstance(f, And):
        parts = [extract_ids(c) for c in f.filters]
        parts = [p for p in parts if p.values or p.disjoint]
        if not parts:
            return FilterValues.nothing()
        ids = set(parts[0].values)
        for p in parts[1:]:
            ids &= set(p.values)
        return FilterValues(values=sorted(ids)) if ids else FilterValues.disjoint_()
    if isinstance(f, Or):
        parts = [extract_ids(c) for c in f.filters]
        if any(p.empty for p in parts):
            return FilterValues.nothing()
        ids: set = set()
        for p in parts:
            ids |= set(p.values)
        return FilterValues(values=sorted(ids))
    return FilterValues.nothing()


# ---------------------------------------------------------------------------
# attribute bounds extraction (for the attribute index)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Bounds:
    """Closed-open attribute value bounds; None = unbounded."""

    lo: object
    hi: object
    lo_inclusive: bool = True
    hi_inclusive: bool = True


def extract_attribute_bounds(f: Filter, prop: str) -> FilterValues:
    """Value bounds on an attribute (reference: extractAttributeBounds)."""
    if isinstance(f, Cmp) and f.prop == prop:
        v = f.value
        if f.op == "=":
            return FilterValues(values=[Bounds(v, v)])
        if f.op == "<":
            return FilterValues(values=[Bounds(None, v, hi_inclusive=False)])
        if f.op == "<=":
            return FilterValues(values=[Bounds(None, v)])
        if f.op == ">":
            return FilterValues(values=[Bounds(v, None, lo_inclusive=False)])
        if f.op == ">=":
            return FilterValues(values=[Bounds(v, None)])
        return FilterValues.nothing()  # <> is not indexable
    if isinstance(f, Between) and f.prop == prop:
        return FilterValues(values=[Bounds(f.lo, f.hi)])
    if isinstance(f, In) and f.prop == prop:
        return FilterValues(values=[Bounds(v, v) for v in f.values])
    if isinstance(f, And):
        all_parts = [extract_attribute_bounds(c, prop) for c in f.filters]
        if any(p.disjoint for p in all_parts):
            return FilterValues.disjoint_()
        imprecise = _imprecise_children(all_parts, f.filters, prop)
        parts = [p for p in all_parts if p.values]
        if not parts:
            return FilterValues.nothing()
        out = parts[0]
        for p in parts[1:]:
            merged = []
            for a in out.values:
                for b in p.values:
                    m = _intersect_bounds(a, b)
                    if m:
                        merged.append(m)
            if not merged:
                return FilterValues.disjoint_()
            out = FilterValues(values=merged, precise=out.precise and p.precise)
        if imprecise:
            out = FilterValues(values=out.values, precise=False)
        return out
    if isinstance(f, Or):
        parts = [extract_attribute_bounds(c, prop) for c in f.filters]
        if any(p.empty for p in parts):
            return FilterValues.nothing()
        vals: list = []
        precise = True
        for p in parts:
            vals.extend(p.values)
            precise &= p.precise
        return FilterValues(values=vals, precise=precise)
    return FilterValues.nothing()


def _intersect_bounds(a: Bounds, b: Bounds) -> Bounds | None:
    lo, lo_inc = a.lo, a.lo_inclusive
    if b.lo is not None and (lo is None or b.lo > lo or (b.lo == lo and not b.lo_inclusive)):
        lo, lo_inc = b.lo, b.lo_inclusive
    hi, hi_inc = a.hi, a.hi_inclusive
    if b.hi is not None and (hi is None or b.hi < hi or (b.hi == hi and not b.hi_inclusive)):
        hi, hi_inc = b.hi, b.hi_inclusive
    if lo is not None and hi is not None:
        if lo > hi or (lo == hi and not (lo_inc and hi_inc)):
            return None
    return Bounds(lo, hi, lo_inc, hi_inc)
