"""Minimal ECQL text parser for the indexable query subset.

The reference parses full (E)CQL via GeoTools' ECQL parser and then
decomposes the tree (geomesa-filter). Here we parse the subset the indexes
accelerate plus general attribute predicates:

    BBOX(geom, -180, -90, 180, 90)
    INTERSECTS(geom, POLYGON ((...)))     [also CONTAINS / WITHIN / DWITHIN]
    dtg DURING 2018-01-01T00:00:00Z/2018-01-08T00:00:00Z
    dtg BEFORE 2018-01-01T00:00:00Z      /  dtg AFTER ...
    dtg BETWEEN '2018-01-01' AND '2018-02-01'
    age > 5, name = 'alice', name IN ('a', 'b'), name LIKE 'a%',
    attr IS NULL, IN ('fid1', 'fid2')    [bare IN = feature-id filter]
    AND / OR / NOT, parentheses, INCLUDE, EXCLUDE

Grammar (precedence low->high): or_expr := and_expr (OR and_expr)* ;
and_expr := not_expr (AND not_expr)* ; not_expr := [NOT] primary.

Dates parse as ISO-8601 (numpy datetime64) to epoch millis.
"""

from __future__ import annotations

import re

import numpy as np

from geomesa_tpu import geometry as geo
from geomesa_tpu.filter.predicates import (
    BBox,
    Between,
    Cmp,
    Contains,
    During,
    DWithin,
    EXCLUDE,
    Filter,
    IdFilter,
    In,
    INCLUDE,
    Intersects,
    IsNull,
    Like,
    Not,
    Or,
    And,
    Within,
)

_TOKEN_RE = re.compile(
    r"""
    \s*(?:
        (?P<string>'(?:[^']|'')*')
      | (?P<datetime>\d{4}-\d{2}-\d{2}T[\d:.]+Z?)
      | (?P<number>-?\d+\.?\d*(?:[eE][+-]?\d+)?)
      | (?P<op><>|<=|>=|=|<|>)
      | (?P<punct>[(),/])
      | (?P<word>[A-Za-z_][A-Za-z0-9_.:]*)
    )
    """,
    re.VERBOSE,
)

_KEYWORDS = {
    "AND", "OR", "NOT", "IN", "LIKE", "IS", "NULL", "BETWEEN", "DURING",
    "BEFORE", "AFTER", "INCLUDE", "EXCLUDE", "BBOX", "INTERSECTS",
    "CONTAINS", "WITHIN", "DWITHIN", "TEQUALS",
}

_GEOM_WORDS = {
    "POINT", "LINESTRING", "POLYGON", "MULTIPOINT", "MULTILINESTRING", "MULTIPOLYGON",
}


def parse_dt_millis(s: str) -> int:
    """ISO-8601 instant -> epoch millis."""
    s = s.strip().rstrip("Z")
    return int(np.datetime64(s, "ms").astype(np.int64))


class _Tok:
    def __init__(self, kind: str, value: str):
        self.kind = kind
        self.value = value

    def __repr__(self):  # pragma: no cover
        return f"{self.kind}:{self.value}"


def _tokenize(text: str) -> list[_Tok]:
    toks: list[_Tok] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if not m or m.end() == pos:
            rest = text[pos:].strip()
            if not rest:
                break
            raise ValueError(f"cannot tokenize ECQL at: {rest[:40]!r}")
        pos = m.end()
        kind = m.lastgroup
        val = m.group(kind)
        toks.append(_Tok(kind, val))
    return toks


class _Parser:
    def __init__(self, text: str):
        self.text = text
        self.toks = _tokenize(text)
        self.i = 0

    # -- token helpers ---------------------------------------------------
    def peek(self) -> _Tok | None:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self) -> _Tok:
        t = self.peek()
        if t is None:
            raise ValueError(f"unexpected end of ECQL: {self.text!r}")
        self.i += 1
        return t

    def accept_word(self, *words: str) -> str | None:
        t = self.peek()
        if t and t.kind == "word" and t.value.upper() in words:
            self.i += 1
            return t.value.upper()
        return None

    def expect_word(self, *words: str) -> str:
        w = self.accept_word(*words)
        if w is None:
            raise ValueError(f"expected {words} at token {self.peek()} in {self.text!r}")
        return w

    def accept_punct(self, p: str) -> bool:
        t = self.peek()
        if t and t.kind == "punct" and t.value == p:
            self.i += 1
            return True
        return False

    def expect_punct(self, p: str):
        if not self.accept_punct(p):
            raise ValueError(f"expected {p!r} at token {self.peek()} in {self.text!r}")

    # -- literals --------------------------------------------------------
    def literal(self):
        t = self.next()
        if t.kind == "string":
            return t.value[1:-1].replace("''", "'")
        if t.kind == "number":
            v = float(t.value)
            return int(v) if v.is_integer() and "." not in t.value and "e" not in t.value.lower() else v
        if t.kind == "datetime":
            return parse_dt_millis(t.value)
        raise ValueError(f"expected literal, got {t}")

    def _maybe_temporal_literal(self, v) -> object:
        """A quoted date string used in BETWEEN etc. parses to millis."""
        if isinstance(v, str):
            try:
                return parse_dt_millis(v) if re.match(r"^\d{4}-\d{2}-\d{2}", v) else v
            except Exception:
                return v
        return v

    # -- grammar ---------------------------------------------------------
    def parse(self) -> Filter:
        f = self.or_expr()
        if self.peek() is not None:
            raise ValueError(f"trailing tokens at {self.peek()} in {self.text!r}")
        return f

    def or_expr(self) -> Filter:
        parts = [self.and_expr()]
        while self.accept_word("OR"):
            parts.append(self.and_expr())
        return parts[0] if len(parts) == 1 else Or(parts)

    def and_expr(self) -> Filter:
        parts = [self.not_expr()]
        while self.accept_word("AND"):
            parts.append(self.not_expr())
        return parts[0] if len(parts) == 1 else And(parts)

    def not_expr(self) -> Filter:
        if self.accept_word("NOT"):
            return Not(self.not_expr())
        return self.primary()

    def primary(self) -> Filter:
        t = self.peek()
        if t is None:
            raise ValueError("unexpected end of ECQL")
        if t.kind == "punct" and t.value == "(":
            self.next()
            f = self.or_expr()
            self.expect_punct(")")
            return f
        if t.kind == "word":
            w = t.value.upper()
            if w == "INCLUDE":
                self.next()
                return INCLUDE
            if w == "EXCLUDE":
                self.next()
                return EXCLUDE
            if w == "BBOX":
                return self.bbox()
            if w in ("INTERSECTS", "CONTAINS", "WITHIN"):
                return self.spatial_binary(w)
            if w == "DWITHIN":
                return self.dwithin()
            if w == "IN":  # bare IN -> feature id filter
                self.next()
                return IdFilter(tuple(self.paren_literals()))
            return self.attribute_predicate()
        raise ValueError(f"unexpected token {t} in {self.text!r}")

    def bbox(self) -> Filter:
        self.expect_word("BBOX")
        self.expect_punct("(")
        prop = self.next().value
        self.expect_punct(",")
        nums = [self.literal()]
        for _ in range(3):
            self.expect_punct(",")
            nums.append(self.literal())
        # optional CRS argument: reproject the box to the store's native
        # 4326 (unsupported CRSs raise — silently dropping the argument
        # would evaluate the box in the wrong CRS)
        crs = None
        if self.accept_punct(","):
            # the CRS may be one quoted string ('EPSG:3857') or unquoted
            # tokens (EPSG : 3857): join everything up to the ')'
            parts = []
            while True:
                t = self.peek()
                if t is None or (t.kind == "punct" and t.value == ")"):
                    break
                parts.append(str(self.next().value))
            crs = "".join(parts).strip("'\"")
        self.expect_punct(")")
        x0, y0, x1, y1 = (float(v) for v in nums)
        if crs is not None:
            from geomesa_tpu.crs import bbox_to_4326

            x0, y0, x1, y1 = bbox_to_4326(x0, y0, x1, y1, crs)
        return BBox(prop, x0, y0, x1, y1)

    def _wkt_geometry(self) -> geo.Geometry:
        t = self.peek()
        if t is None or t.kind != "word" or t.value.upper() not in _GEOM_WORDS:
            raise ValueError(f"expected WKT geometry at {t}")
        # re-lex from the raw text: find the geometry substring by paren balance
        # locate the current token's position in the original text
        word = self.next().value
        # find the text position after tokens consumed so far: rebuild by
        # scanning for the word followed by '('
        # simpler: reconstruct WKT from tokens until parens balance
        depth = 0
        parts = [word]
        started = False
        while True:
            t = self.next()
            if t.kind == "punct" and t.value == "(":
                depth += 1
                started = True
                parts.append("(")
            elif t.kind == "punct" and t.value == ")":
                depth -= 1
                parts.append(")")
                if started and depth == 0:
                    break
            elif t.kind == "punct" and t.value == ",":
                parts.append(",")
            elif t.kind == "number":
                parts.append(t.value + " ")
            else:
                parts.append(t.value + " ")
        return geo.from_wkt(
            "".join(parts).replace(" ,", ",").replace(" )", ")")
        )

    def spatial_binary(self, op: str) -> Filter:
        self.expect_word(op)
        self.expect_punct("(")
        prop = self.next().value
        self.expect_punct(",")
        g = self._wkt_geometry()
        self.expect_punct(")")
        cls = {"INTERSECTS": Intersects, "CONTAINS": Contains, "WITHIN": Within}[op]
        return cls(prop, g)

    def dwithin(self) -> Filter:
        self.expect_word("DWITHIN")
        self.expect_punct("(")
        prop = self.next().value
        self.expect_punct(",")
        g = self._wkt_geometry()
        self.expect_punct(",")
        dist = float(self.literal())
        # optional units argument (meters/kilometers/statute miles...); we
        # store planar degrees like the reference's fallback path
        if self.accept_punct(","):
            units = self.next().value.lower()
            # two-word units: "statute miles" / "nautical miles"
            nxt = self.peek()
            if nxt is not None and nxt.kind == "word" and nxt.value.lower() == "miles":
                units = f"{units} {self.next().value.lower()}"
            dist = _to_degrees(dist, units)
        self.expect_punct(")")
        return DWithin(prop, g, dist)

    def paren_literals(self) -> list:
        self.expect_punct("(")
        vals = [self.literal()]
        while self.accept_punct(","):
            vals.append(self.literal())
        self.expect_punct(")")
        return vals

    def attribute_predicate(self) -> Filter:
        prop = self.next().value
        t = self.peek()
        if t is None:
            raise ValueError(f"dangling property {prop!r}")
        if t.kind == "op":
            op = self.next().value
            v = self._maybe_temporal_literal(self.literal())
            return Cmp(prop, op, v)
        w = t.value.upper() if t.kind == "word" else None
        if w == "DURING":
            self.next()
            lo = self.next()
            self.expect_punct("/")
            hi = self.next()
            return During(prop, parse_dt_millis(lo.value), parse_dt_millis(hi.value))
        if w == "BEFORE":
            self.next()
            return Cmp(prop, "<", parse_dt_millis(self.next().value))
        if w == "AFTER":
            self.next()
            return Cmp(prop, ">", parse_dt_millis(self.next().value))
        if w == "TEQUALS":
            self.next()
            return Cmp(prop, "=", parse_dt_millis(self.next().value))
        if w == "BETWEEN":
            self.next()
            lo = self._maybe_temporal_literal(self.literal())
            self.expect_word("AND")
            hi = self._maybe_temporal_literal(self.literal())
            return Between(prop, lo, hi)
        if w == "IN":
            self.next()
            return In(prop, tuple(self.paren_literals()))
        if w == "LIKE":
            self.next()
            pat = self.literal()
            return Like(prop, str(pat))
        if w == "IS":
            self.next()
            if self.accept_word("NOT"):
                self.expect_word("NULL")
                return Not(IsNull(prop))
            self.expect_word("NULL")
            return IsNull(prop)
        if w == "NOT":
            self.next()
            inner = self.attribute_predicate_continued(prop)
            return Not(inner)
        raise ValueError(f"unexpected predicate on {prop!r}: {t}")

    def attribute_predicate_continued(self, prop: str) -> Filter:
        """Handles `prop NOT IN (...)` / `prop NOT LIKE ...` / `NOT BETWEEN`."""
        if self.accept_word("IN"):
            return In(prop, tuple(self.paren_literals()))
        if self.accept_word("LIKE"):
            return Like(prop, str(self.literal()))
        if self.accept_word("BETWEEN"):
            lo = self._maybe_temporal_literal(self.literal())
            self.expect_word("AND")
            hi = self._maybe_temporal_literal(self.literal())
            return Between(prop, lo, hi)
        raise ValueError(f"unexpected NOT clause on {prop!r}")


_METERS_PER_DEGREE = 111_320.0


def _to_degrees(dist: float, units: str) -> float:
    """Convert a DWITHIN distance to approximate planar degrees at the
    equator (the reference treats geographic DWITHIN similarly loosely)."""
    scales = {
        "meters": 1.0,
        "m": 1.0,
        "kilometers": 1000.0,
        "km": 1000.0,
        "feet": 0.3048,
        "ft": 0.3048,
        "statute miles": 1609.34,
        "miles": 1609.34,
        "mi": 1609.34,
        "nautical miles": 1852.0,
        "nm": 1852.0,
        "degrees": _METERS_PER_DEGREE,
    }
    if units not in scales:
        raise ValueError(f"unknown DWITHIN units {units!r}")
    return dist * scales[units] / _METERS_PER_DEGREE


def parse(text: str) -> Filter:
    """Parse an ECQL string into a Filter tree."""
    return _Parser(text).parse()
