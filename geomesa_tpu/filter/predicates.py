"""Filter predicate AST with vectorized (columnar) evaluation.

The reference represents queries as GeoTools/ECQL `Filter` trees and
evaluates them per-feature through JTS + FastFilterFactory
(/root/reference/geomesa-filter/src/main/scala/org/locationtech/geomesa/
filter/factory/FastFilterFactory.scala). The TPU redesign keeps the same
logical algebra (And/Or/Not over spatial, temporal, attribute and id
predicates) but evaluation is *columnar*: ``Filter.evaluate(batch)`` returns
a boolean mask over a whole batch of features at once. The device scan
kernels implement the same semantics over jnp columns for the push-down
tier; this host path is the exactness reference and the fallback for
predicates the device can't run.

Geometry columns in a batch are either a ``PointColumn`` (struct-of-arrays
x/y — the point fast path) or a ``PackedGeometryColumn`` (extents).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Mapping, Sequence

import numpy as np

from geomesa_tpu import geometry as geo


@dataclass(frozen=True)
class PointColumn:
    """Struct-of-arrays geometry column for point features."""

    x: np.ndarray
    y: np.ndarray

    def __len__(self) -> int:
        return len(self.x)


GeometryColumn = "PointColumn | geo.PackedGeometryColumn"


class Filter:
    """Base predicate. Subclasses are frozen dataclasses."""

    def evaluate(self, batch: Mapping[str, object]) -> np.ndarray:
        """Boolean mask over the batch (dict: attr name -> column)."""
        raise NotImplementedError

    # -- algebra sugar ---------------------------------------------------
    def __and__(self, other: "Filter") -> "Filter":
        return And((self, other))

    def __or__(self, other: "Filter") -> "Filter":
        return Or((self, other))

    def __invert__(self) -> "Filter":
        return Not(self)


def _batch_len(batch: Mapping[str, object]) -> int:
    for v in batch.values():
        if isinstance(v, (PointColumn, geo.PackedGeometryColumn)):
            return len(v)
        return len(v)
    return 0


def _column(batch: Mapping[str, object], prop: str) -> np.ndarray:
    try:
        return batch[prop]
    except KeyError:
        raise KeyError(f"no column {prop!r} in batch (have {list(batch)})")


@dataclass(frozen=True)
class Include(Filter):
    """Matches everything (ECQL INCLUDE)."""

    def evaluate(self, batch):
        return np.ones(_batch_len(batch), dtype=bool)


@dataclass(frozen=True)
class Exclude(Filter):
    """Matches nothing (ECQL EXCLUDE)."""

    def evaluate(self, batch):
        return np.zeros(_batch_len(batch), dtype=bool)


INCLUDE = Include()
EXCLUDE = Exclude()


# ---------------------------------------------------------------------------
# spatial
# ---------------------------------------------------------------------------


def _ulp_out(x0: float, y0: float, x1: float, y1: float):
    """Bounds widened one f32 ulp outward — matching the widening the
    packed column applied to its stored bboxes, so bbox prefilters built
    on >=/<= comparisons stay conservative."""
    lo = np.nextafter(np.array([x0, y0], dtype=np.float32), -np.inf).astype(np.float64)
    hi = np.nextafter(np.array([x1, y1], dtype=np.float32), np.inf).astype(np.float64)
    return float(lo[0]), float(lo[1]), float(hi[0]), float(hi[1])


def _eval_spatial(col, fn_points, fn_geom, candidates=None) -> np.ndarray:
    """Exact per-geometry evaluation over a packed column, restricted to
    ``candidates`` (a bool mask from a vectorized bbox prefilter — rows
    outside it are definitively False)."""
    if isinstance(col, PointColumn):
        return fn_points(col.x, col.y)
    if isinstance(col, geo.PackedGeometryColumn):
        out = np.zeros(len(col), dtype=bool)
        rows = range(len(col)) if candidates is None else np.nonzero(candidates)[0]
        for i in rows:
            out[i] = fn_geom(col.geometry(int(i)))
        return out
    raise TypeError(f"not a geometry column: {type(col)}")


def _per_geom_vertex_counts(col: "geo.PackedGeometryColumn", vertex_mask):
    """How many of each geometry's pool vertices satisfy ``vertex_mask``
    ([total_verts] bool) — the cumsum reduction over the contiguous
    per-geometry coord slices."""
    csum = np.concatenate([[0], np.cumsum(vertex_mask)])
    first_ring = col.part_ring_offsets[col.geom_part_offsets].astype(np.int64)
    bounds_ix = col.ring_offsets[first_ring].astype(np.int64)
    return csum[bounds_ix[1:]] - csum[bounds_ix[:-1]]


@dataclass(frozen=True)
class BBox(Filter):
    """BBOX(prop, xmin, ymin, xmax, ymax) — geometry interacts with the box.

    Reference: the `bbox` spatial op extracted by FilterHelper
    (geomesa-filter/.../FilterHelper.scala:100-130).
    """

    prop: str
    xmin: float
    ymin: float
    xmax: float
    ymax: float

    @property
    def bounds(self) -> tuple[float, float, float, float]:
        return (self.xmin, self.ymin, self.xmax, self.ymax)

    def evaluate(self, batch):
        col = _column(batch, self.prop)
        if isinstance(col, PointColumn):
            return (
                (col.x >= self.xmin)
                & (col.x <= self.xmax)
                & (col.y >= self.ymin)
                & (col.y <= self.ymax)
            )
        if isinstance(col, geo.PackedGeometryColumn):
            q = np.array(self.bounds)
            bx = geo.box(*self.bounds)
            return _packed_box_intersects(col, q, bx)
        raise TypeError(f"not a geometry column: {type(col)}")


def _packed_box_intersects(
    col: "geo.PackedGeometryColumn", q: np.ndarray, g: "geo.Geometry"
) -> np.ndarray:
    """Geometry-intersects-axis-aligned-box over a packed column.

    Rectangle features (geometry == bbox: footprints, tiles, extents)
    resolve exactly with vectorized f64 bbox algebra; only non-rectangle
    candidates fall to per-geometry exact tests."""
    rough = geo.bbox_intersects(col.bboxes.astype(np.float64), q)
    bmask, bb = col.box_info()
    out = (
        bmask
        & (bb[:, 0] <= q[2]) & (bb[:, 2] >= q[0])
        & (bb[:, 1] <= q[3]) & (bb[:, 3] >= q[1])
    )
    hard = rough & ~bmask
    n_hard = int(hard.sum())
    if 0 < n_hard <= 64:
        # a handful of non-rect candidates (e.g. a few odd polygons in a
        # mostly-rectangle column): the per-geometry loop beats scanning
        # the whole coords pool
        for i in np.nonzero(hard)[0]:
            out[i] = geo.intersects(col.geometry(int(i)), g)
    elif n_hard:
        # vectorized accept tier for arbitrary (non-rectangle) geometries:
        # the query here is ALWAYS an axis-aligned rect (both call sites
        # gate on is_rectangle), so any geometry VERTEX inside it proves
        # intersection. Each geometry's coords are one contiguous pool
        # slice; a cumsum turns the per-vertex test into per-geometry
        # counts. Only vertex-free overlaps (rect fully inside the
        # geometry, or pure edge crossings) fall to the per-geometry loop.
        c = col.coords
        inb = (
            (c[:, 0] >= q[0]) & (c[:, 0] <= q[2])
            & (c[:, 1] >= q[1]) & (c[:, 1] <= q[3])
        )
        any_vertex = _per_geom_vertex_counts(col, inb) > 0
        out |= hard & any_vertex
        for i in np.nonzero(hard & ~any_vertex)[0]:
            out[i] = geo.intersects(col.geometry(int(i)), g)
    return out


@dataclass(frozen=True)
class Intersects(Filter):
    """INTERSECTS(prop, <geometry>)."""

    prop: str
    geom: geo.Geometry

    def evaluate(self, batch):
        col = _column(batch, self.prop)
        g = self.geom
        if isinstance(col, PointColumn):
            # vectorized bbox prefilter bounds the per-point work to
            # near-hit points (the Python loops below are exact but slow)
            x0, y0, x1, y1 = g.bounds()
            near = (col.x >= x0) & (col.x <= x1) & (col.y >= y0) & (col.y <= y1)
            if isinstance(g, (geo.Polygon, geo.MultiPolygon)):
                inside = np.zeros(len(col), dtype=bool)
                ni = np.nonzero(near)[0]
                inside[ni] = geo.points_in_polygon(col.x[ni], col.y[ni], g)
                # boundary counts for intersects — vectorized over the
                # near-but-not-inside candidates (a per-point loop here
                # cost seconds on dense bbox-near outside regions)
                nb = ni[~inside[ni]]
                if len(nb):
                    inside[nb] = geo.points_on_boundary(
                        col.x[nb], col.y[nb], g
                    )
                return inside
            out = np.zeros(len(col), dtype=bool)
            for i in np.nonzero(near)[0]:
                out[i] = geo.intersects(geo.Point(float(col.x[i]), float(col.y[i])), g)
            return out
        if isinstance(col, geo.PackedGeometryColumn):
            q = np.array(g.bounds())
            if geo.is_rectangle(g):
                return _packed_box_intersects(col, q, g)
            rough = geo.bbox_intersects(col.bboxes.astype(np.float64), q)
            out = np.zeros(len(col), dtype=bool)
            n_rough = int(rough.sum())
            if n_rough > 64 and isinstance(g, (geo.Polygon, geo.MultiPolygon)):
                # accept tier for a POLYGON query over arbitrary features:
                # any feature vertex inside the query polygon proves
                # intersection (one native ray cast over the coords pool)
                c = col.coords
                inside = geo.points_in_polygon(c[:, 0], c[:, 1], g)
                n_in = _per_geom_vertex_counts(col, inside)
                out |= rough & (n_in > 0)
                rough &= ~out
            for i in np.nonzero(rough)[0]:
                out[i] = geo.intersects(col.geometry(int(i)), g)
            return out
        raise TypeError(f"not a geometry column: {type(col)}")


@dataclass(frozen=True)
class Within(Filter):
    """WITHIN(prop, <geometry>): the feature lies within the query geometry."""

    prop: str
    geom: geo.Geometry

    def evaluate(self, batch):
        col = _column(batch, self.prop)
        g = self.geom
        if not isinstance(g, (geo.Polygon, geo.MultiPolygon)):
            raise ValueError("WITHIN requires a polygonal query geometry")
        if isinstance(col, PointColumn):
            return geo.points_in_polygon(col.x, col.y, g)
        # necessary condition, vectorized: the feature's bbox lies inside
        # the query's bbox (within implies bbox containment). Stored
        # bboxes are f32-widened one ulp OUTWARD, so the query bounds
        # widen by an ulp too — no true-within row is ever excluded;
        # extra grazers fall to the exact check below.
        x0, y0, x1, y1 = _ulp_out(*g.bounds())
        b = col.bboxes.astype(np.float64)
        cand = (b[:, 0] >= x0) & (b[:, 1] >= y0) & (b[:, 2] <= x1) & (b[:, 3] <= y1)
        if geo.is_rectangle(g):
            # two-tier for a rect query: rows whose OUTWARD-widened stored
            # bbox fits inside the RAW query bounds are definitely within
            # (true bbox subset of stored; boundary contact allowed, as
            # JTS `within` permits boundary points). Only the sub-ulp
            # boundary band (cand minus sure) needs the exact check, so
            # a protruding vertex 1 ulp past the edge is never accepted.
            rx0, ry0, rx1, ry1 = g.bounds()
            sure = (
                (b[:, 0] >= rx0) & (b[:, 1] >= ry0)
                & (b[:, 2] <= rx1) & (b[:, 3] <= ry1)
            )
            out = _eval_spatial(
                col, None, lambda feat: geo.contains(g, feat),
                candidates=cand & ~sure,
            )
            return out | sure
        return _eval_spatial(
            col, None, lambda feat: geo.contains(g, feat), candidates=cand
        )


@dataclass(frozen=True)
class Contains(Filter):
    """CONTAINS(prop, <geometry>): the feature contains the query geometry."""

    prop: str
    geom: geo.Geometry

    def evaluate(self, batch):
        col = _column(batch, self.prop)
        if isinstance(col, PointColumn):
            if isinstance(self.geom, geo.Point):
                return (col.x == self.geom.x) & (col.y == self.geom.y)
            return np.zeros(len(col), dtype=bool)
        # necessary condition, vectorized: the feature's bbox covers the
        # query geometry's bbox (stored bboxes widen outward, so the
        # direct comparison is already conservative for covering)
        x0, y0, x1, y1 = self.geom.bounds()
        b = col.bboxes.astype(np.float64)
        cand = (b[:, 0] <= x0) & (b[:, 1] <= y0) & (b[:, 2] >= x1) & (b[:, 3] >= y1)
        return _eval_spatial(
            col, None, lambda feat: isinstance(feat, (geo.Polygon, geo.MultiPolygon))
            and geo.contains(feat, self.geom),
            candidates=cand,
        )


@dataclass(frozen=True)
class DWithin(Filter):
    """DWITHIN(prop, <geometry>, distance): within planar distance."""

    prop: str
    geom: geo.Geometry
    dist: float

    def evaluate(self, batch):
        col = _column(batch, self.prop)
        if isinstance(col, PointColumn):
            if isinstance(self.geom, geo.Point):
                return np.hypot(col.x - self.geom.x, col.y - self.geom.y) <= self.dist
            # bbox prefilter: only points inside the distance-expanded
            # envelope can be within range
            x0, y0, x1, y1 = self.bounds
            near = (col.x >= x0) & (col.x <= x1) & (col.y >= y0) & (col.y <= y1)
            out = np.zeros(len(col), dtype=bool)
            for i in np.nonzero(near)[0]:
                out[i] = (
                    geo._point_geom_distance(float(col.x[i]), float(col.y[i]), self.geom)
                    <= self.dist
                )
            return out
        x0, y0, x1, y1 = _ulp_out(*self.bounds)
        b = col.bboxes.astype(np.float64)
        cand = (b[:, 0] <= x1) & (b[:, 2] >= x0) & (b[:, 1] <= y1) & (b[:, 3] >= y0)
        return _eval_spatial(
            col, None, lambda feat: geo.distance(feat, self.geom) <= self.dist,
            candidates=cand,
        )

    @property
    def bounds(self) -> tuple[float, float, float, float]:
        x0, y0, x1, y1 = self.geom.bounds()
        return (x0 - self.dist, y0 - self.dist, x1 + self.dist, y1 + self.dist)


# ---------------------------------------------------------------------------
# temporal (epoch-millis int64 columns)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class During(Filter):
    """prop DURING lo/hi — half-open [lo, hi) on epoch millis, matching the
    reference's During semantics (FilterHelper.extractIntervals treats During
    as exclusive bounds; we use inclusive-lo/exclusive-hi which matches how
    GeoMesa plans Z3 ranges in practice)."""

    prop: str
    lo_ms: int
    hi_ms: int

    def evaluate(self, batch):
        c = np.asarray(_column(batch, self.prop), dtype=np.int64)
        return (c >= self.lo_ms) & (c < self.hi_ms)


# ---------------------------------------------------------------------------
# attribute comparisons
# ---------------------------------------------------------------------------

_OPS = {"=", "<>", "<", "<=", ">", ">="}


def _is_str_col(c: np.ndarray) -> bool:
    return c.dtype.kind in ("U", "S", "O")


@dataclass(frozen=True)
class Cmp(Filter):
    """prop <op> literal, op in =, <>, <, <=, >, >=."""

    prop: str
    op: str
    value: object

    def __post_init__(self):
        if self.op not in _OPS:
            raise ValueError(f"bad op {self.op!r}")

    def evaluate(self, batch):
        c = _column(batch, self.prop)
        c = np.asarray(c)
        v = self.value
        if self.op == "=":
            return c == v
        if self.op == "<>":
            return c != v
        if self.op == "<":
            return c < v
        if self.op == "<=":
            return c <= v
        if self.op == ">":
            return c > v
        return c >= v


@dataclass(frozen=True)
class Between(Filter):
    """prop BETWEEN lo AND hi (inclusive both ends, per ECQL)."""

    prop: str
    lo: object
    hi: object

    def evaluate(self, batch):
        c = np.asarray(_column(batch, self.prop))
        return (c >= self.lo) & (c <= self.hi)


@dataclass(frozen=True)
class In(Filter):
    """prop IN (v1, v2, ...)."""

    prop: str
    values: tuple

    def evaluate(self, batch):
        c = np.asarray(_column(batch, self.prop))
        return np.isin(c, np.asarray(list(self.values)))


@dataclass(frozen=True)
class Like(Filter):
    """prop LIKE 'pattern' with % (any) and _ (one) wildcards."""

    prop: str
    pattern: str

    def _regex(self) -> re.Pattern:
        esc = re.escape(self.pattern).replace("%", ".*").replace("_", ".")
        return re.compile(f"^{esc}$")

    def evaluate(self, batch):
        c = np.asarray(_column(batch, self.prop))
        rx = self._regex()
        return np.array([bool(rx.match(str(v))) for v in c], dtype=bool)


@dataclass(frozen=True)
class IsNull(Filter):
    """prop IS NULL — NaN for floats, sentinel '' for strings, NaT dates."""

    prop: str

    def evaluate(self, batch):
        c = np.asarray(_column(batch, self.prop))
        if c.dtype.kind == "f":
            return np.isnan(c)
        if _is_str_col(c):
            return np.array([v == "" or v is None for v in c], dtype=bool)
        return np.zeros(len(c), dtype=bool)


@dataclass(frozen=True)
class IdFilter(Filter):
    """Feature-id lookup (ECQL `IN ('id1', 'id2')` without a property).

    Reference: IdFilterStrategy / IdIndexKeySpace.
    """

    ids: tuple

    def evaluate(self, batch):
        fids = batch.get("__id__")
        if fids is None:
            raise KeyError("batch has no __id__ column for id filter")
        fids = np.asarray(fids)
        want = np.asarray(list(self.ids))
        if fids.dtype.kind != want.dtype.kind:
            # ECQL id literals are strings; stored ids may be numeric —
            # compare canonically as strings
            fids = fids.astype(str)
            want = want.astype(str)
        return np.isin(fids, want)


# ---------------------------------------------------------------------------
# logical
# ---------------------------------------------------------------------------


def _flatten(cls, filters: Sequence[Filter]) -> tuple[Filter, ...]:
    out: list[Filter] = []
    for f in filters:
        if isinstance(f, cls):
            out.extend(f.filters)
        else:
            out.append(f)
    return tuple(out)


@dataclass(frozen=True)
class And(Filter):
    filters: tuple = ()

    def __init__(self, filters: Sequence[Filter]):
        object.__setattr__(self, "filters", _flatten(And, tuple(filters)))
        if len(self.filters) < 1:
            raise ValueError("And needs >= 1 children")

    def evaluate(self, batch):
        m = self.filters[0].evaluate(batch)
        for f in self.filters[1:]:
            m = m & f.evaluate(batch)
        return m


@dataclass(frozen=True)
class Or(Filter):
    filters: tuple = ()

    def __init__(self, filters: Sequence[Filter]):
        object.__setattr__(self, "filters", _flatten(Or, tuple(filters)))
        if len(self.filters) < 1:
            raise ValueError("Or needs >= 1 children")

    def evaluate(self, batch):
        m = self.filters[0].evaluate(batch)
        for f in self.filters[1:]:
            m = m | f.evaluate(batch)
        return m


@dataclass(frozen=True)
class Not(Filter):
    filter: Filter = None  # type: ignore[assignment]

    def evaluate(self, batch):
        return ~self.filter.evaluate(batch)


def canonical_key(f: Filter) -> str:
    """Deterministic canonical string of a filter tree. Logically-equal
    trees that differ only in And/Or child ORDER produce the SAME string
    (children sort by their own canonical keys), so cache fingerprints and
    plan comparisons treat ``a AND b`` and ``b AND a`` as one query.
    Geometries render as WKT; floats as repr (round-trip exact)."""
    if isinstance(f, (And, Or)):
        kids = sorted(canonical_key(c) for c in f.filters)
        return f"{type(f).__name__}({','.join(kids)})"
    if isinstance(f, Not):
        return f"Not({canonical_key(f.filter)})"
    from dataclasses import fields, is_dataclass

    if not is_dataclass(f):  # pragma: no cover - all predicates are dataclasses
        return repr(f)
    parts = [
        f"{fd.name}={_canonical_value(getattr(f, fd.name))}" for fd in fields(f)
    ]
    return f"{type(f).__name__}({','.join(parts)})"


def _canonical_value(v) -> str:
    if isinstance(v, geo.Geometry):
        return v.wkt
    if isinstance(v, (bool, np.bool_)):
        return repr(bool(v))
    if isinstance(v, (float, np.floating)):
        return repr(float(v))
    if isinstance(v, (int, np.integer)):
        return repr(int(v))
    if isinstance(v, (tuple, list)):
        return "(" + ",".join(_canonical_value(x) for x in v) + ")"
    return repr(v)


def wrap_box(prop: str, x0: float, y0: float, x1: float, y1: float) -> Filter:
    """A lon/lat box as a filter, WRAPPING across the antimeridian
    (GeoTools BBOX semantics: a box past +/-180 crosses the seam and
    becomes two boxes). Latitude clamps to [-90, 90]."""
    import math

    y0, y1 = max(y0, -90.0), min(y1, 90.0)
    if not (math.isfinite(x0) and math.isfinite(x1)):
        # non-finite lons (e.g. an overflowed literal): keep the raw box —
        # the shift loops below would never terminate on inf
        return BBox(prop, x0, y0, x1, y1)
    if x1 - x0 >= 360.0:
        return BBox(prop, -180.0, y0, 180.0, y1)
    # a box lying ENTIRELY beyond the seam shifts into range first — the
    # splits below would otherwise emit an inverted (xmin > xmax) arm
    while x0 > 180.0:
        x0 -= 360.0
        x1 -= 360.0
    while x1 < -180.0:
        x0 += 360.0
        x1 += 360.0
    if x0 < -180.0:
        return Or((
            BBox(prop, -180.0, y0, x1, y1),
            BBox(prop, x0 + 360.0, y0, 180.0, y1),
        ))
    if x1 > 180.0:
        return Or((
            BBox(prop, x0, y0, 180.0, y1),
            BBox(prop, -180.0, y0, x1 - 360.0, y1),
        ))
    return BBox(prop, x0, y0, x1, y1)


def normalize_antimeridian(f: Filter) -> Filter:
    """Rewrite out-of-range BBOXes anywhere in a filter tree into their
    wrapped two-box form (reference FilterHelper splits seam-crossing
    boxes the same way; without this the planner's world-clamping would
    silently drop the wrapped part). Returns ``f`` itself when nothing
    in the tree needed rewriting (the common case on every plan())."""
    if isinstance(f, BBox) and (f.xmin < -180.0 or f.xmax > 180.0):
        return wrap_box(f.prop, f.xmin, f.ymin, f.xmax, f.ymax)
    if isinstance(f, (And, Or)):
        kids = tuple(normalize_antimeridian(c) for c in f.filters)
        if all(k is c for k, c in zip(kids, f.filters)):
            return f
        return And(kids) if isinstance(f, And) else Or(kids)
    if isinstance(f, Not):
        inner = normalize_antimeridian(f.filter)
        return f if inner is f.filter else Not(inner)
    return f
