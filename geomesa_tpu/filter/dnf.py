"""Disjunctive-normal-form rewrite for union planning.

Reference: FilterSplitter rewrites filters into DNF before computing query
options, so each disjunct can pick its own index and the results union
(/root/reference/geomesa-filter/src/main/scala/org/locationtech/geomesa/
filter/package.scala `rewriteFilterInDnf` + geomesa-index-api/.../planning/
FilterSplitter.scala:61-147 — `(bbox AND a=1) OR (b=2)` becomes one
spatial-index option and one attribute-index option with deduplication).

The expansion is capped: distributing ANDs over ORs is exponential in the
worst case, and past a handful of disjuncts a union plan loses to a single
scan anyway (the reference caps at 32 options and falls back to a single
full-filter strategy the same way).
"""

from __future__ import annotations

from geomesa_tpu.filter.predicates import And, Filter, Not, Or

MAX_DISJUNCTS = 16


def rewrite_dnf(f: Filter, limit: int = MAX_DISJUNCTS) -> list[Filter] | None:
    """``f`` as a bounded list of disjuncts (each free of top-level ORs),
    or None when the expansion would exceed ``limit`` disjuncts.

    NOT is pushed through And/Or by De Morgan; other predicates are leaves.
    A single-element result means the filter has no OR structure at all.
    """
    out = _dnf(_push_not(f), limit)
    return out


def _push_not(f: Filter) -> Filter:
    """De Morgan: push NOT down to the leaves so distribution sees the
    whole And/Or structure."""
    if isinstance(f, Not):
        inner = f.filter
        if isinstance(inner, And):
            return _push_not(Or([Not(c) for c in inner.filters]))
        if isinstance(inner, Or):
            return _push_not(And([Not(c) for c in inner.filters]))
        if isinstance(inner, Not):
            return _push_not(inner.filter)
        return f
    if isinstance(f, And):
        return And([_push_not(c) for c in f.filters])
    if isinstance(f, Or):
        return Or([_push_not(c) for c in f.filters])
    return f


def _dnf(f: Filter, limit: int) -> list[Filter] | None:
    if isinstance(f, Or):
        out: list[Filter] = []
        for c in f.filters:
            part = _dnf(c, limit)
            if part is None:
                return None
            out.extend(part)
            if len(out) > limit:
                return None
        return out
    if isinstance(f, And):
        # cross-product of the children's disjunct lists
        terms: list[list[Filter]] = [[]]
        for c in f.filters:
            part = _dnf(c, limit)
            if part is None:
                return None
            terms = [t + [d] for t in terms for d in part]
            if len(terms) > limit:
                return None
        return [t[0] if len(t) == 1 else And(t) for t in terms]
    return [f]
