"""Filter model: predicate AST, ECQL parsing, and index-value extraction.

The analogue of the reference's `geomesa-filter` module (SURVEY.md section
2.3): decompose CQL into the geometries/intervals/bounds the indexes can
accelerate, and evaluate the full predicate tree columnar-batch-wise for
exact refinement.
"""

from geomesa_tpu.filter.predicates import (
    And,
    BBox,
    Between,
    Cmp,
    Contains,
    During,
    DWithin,
    EXCLUDE,
    Exclude,
    Filter,
    IdFilter,
    In,
    INCLUDE,
    Include,
    Intersects,
    IsNull,
    Like,
    Not,
    Or,
    PointColumn,
    Within,
)
from geomesa_tpu.filter.ecql import parse, parse_dt_millis
from geomesa_tpu.filter.extract import (
    Bounds,
    FilterValues,
    Interval,
    extract_attribute_bounds,
    extract_geometries,
    extract_ids,
    extract_intervals,
    geometry_bounds,
)

__all__ = [
    "Filter", "Include", "Exclude", "INCLUDE", "EXCLUDE",
    "BBox", "Intersects", "Contains", "Within", "DWithin",
    "During", "Cmp", "Between", "In", "Like", "IsNull", "IdFilter",
    "And", "Or", "Not", "PointColumn",
    "parse", "parse_dt_millis",
    "FilterValues", "Interval", "Bounds",
    "extract_geometries", "extract_intervals", "extract_ids",
    "extract_attribute_bounds", "geometry_bounds",
]
