"""Geometry model: host objects, WKT/WKB codecs, packed columnar storage,
and vectorized predicate math.

The reference represents geometries as JTS objects serialized per-feature
via TWKB/WKB (/root/reference/geomesa-features/geomesa-feature-common/src/main/
scala/org/locationtech/geomesa/features/serialization/TwkbSerialization.scala,
WkbSerialization.scala) and evaluates predicates through JTS inside the
filter stack. The TPU redesign inverts that: geometries live in an
Arrow-style *packed columnar pool* (flat coordinate array + nested offset
arrays), per-geometry bounding boxes are precomputed f32 device columns for
the scan prefilter, and the exact predicates (point-in-polygon, segment
intersection) are vectorized numpy here with jnp twins in
geomesa_tpu.sql.stfuncs for on-device refinement.

No shapely/JTS anywhere — predicates are re-derived from the standard
computational-geometry constructions (even-odd ray casting, orientation
tests).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Sequence

import numpy as np

# geometry type codes (shared by WKB and the packed column `types` array)
POINT = 1
LINESTRING = 2
POLYGON = 3
MULTIPOINT = 4
MULTILINESTRING = 5
MULTIPOLYGON = 6

TYPE_NAMES = {
    POINT: "Point",
    LINESTRING: "LineString",
    POLYGON: "Polygon",
    MULTIPOINT: "MultiPoint",
    MULTILINESTRING: "MultiLineString",
    MULTIPOLYGON: "MultiPolygon",
}
TYPE_CODES = {v.upper(): k for k, v in TYPE_NAMES.items()}


# ---------------------------------------------------------------------------
# host geometry objects
# ---------------------------------------------------------------------------


class Geometry:
    """Base host geometry. Subclasses hold numpy coordinate arrays."""

    type_code: int

    @property
    def geom_type(self) -> str:
        return TYPE_NAMES[self.type_code]

    def bounds(self) -> tuple[float, float, float, float]:
        raise NotImplementedError

    @property
    def wkt(self) -> str:
        return to_wkt(self)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return self.wkt if self._coord_count() <= 12 else f"<{self.geom_type} ({self._coord_count()} pts)>"

    def _coord_count(self) -> int:
        raise NotImplementedError

    def __eq__(self, other) -> bool:
        return isinstance(other, Geometry) and self.wkt == other.wkt

    def __hash__(self) -> int:
        return hash(self.wkt)


def _coords(arr) -> np.ndarray:
    a = np.asarray(arr, dtype=np.float64)
    if a.ndim != 2 or a.shape[1] != 2:
        raise ValueError(f"coordinates must be [n, 2]: got shape {a.shape}")
    return a


class Point(Geometry):
    type_code = POINT

    def __init__(self, x: float, y: float):
        self.x = float(x)
        self.y = float(y)

    def bounds(self):
        return (self.x, self.y, self.x, self.y)

    def _coord_count(self):
        return 1


class LineString(Geometry):
    type_code = LINESTRING

    def __init__(self, coords):
        self.coords = _coords(coords)
        if len(self.coords) < 2:
            raise ValueError("LineString needs >= 2 points")

    def bounds(self):
        return (
            float(self.coords[:, 0].min()),
            float(self.coords[:, 1].min()),
            float(self.coords[:, 0].max()),
            float(self.coords[:, 1].max()),
        )

    def _coord_count(self):
        return len(self.coords)

    @property
    def length(self) -> float:
        d = np.diff(self.coords, axis=0)
        return float(np.sqrt((d**2).sum(axis=1)).sum())


class Polygon(Geometry):
    """Shell + holes, each a closed ring (first point == last point; the
    constructor closes unclosed rings)."""

    type_code = POLYGON

    def __init__(self, shell, holes: Sequence | None = None):
        self.shell = _close_ring(_coords(shell))
        self.holes = [_close_ring(_coords(h)) for h in (holes or [])]

    def bounds(self):
        return (
            float(self.shell[:, 0].min()),
            float(self.shell[:, 1].min()),
            float(self.shell[:, 0].max()),
            float(self.shell[:, 1].max()),
        )

    def _coord_count(self):
        return len(self.shell) + sum(len(h) for h in self.holes)

    @property
    def area(self) -> float:
        a = _ring_area(self.shell)
        return abs(a) - sum(abs(_ring_area(h)) for h in self.holes)


class _Multi(Geometry):
    part_type: type

    def __init__(self, parts: Iterable):
        self.parts = list(parts)
        for p in self.parts:
            if not isinstance(p, self.part_type):
                raise ValueError(f"{self.geom_type} parts must be {self.part_type.__name__}")

    def bounds(self):
        bs = np.array([p.bounds() for p in self.parts])
        return (
            float(bs[:, 0].min()),
            float(bs[:, 1].min()),
            float(bs[:, 2].max()),
            float(bs[:, 3].max()),
        )

    def _coord_count(self):
        return sum(p._coord_count() for p in self.parts)


class MultiPoint(_Multi):
    type_code = MULTIPOINT
    part_type = Point


class MultiLineString(_Multi):
    type_code = MULTILINESTRING
    part_type = LineString


class MultiPolygon(_Multi):
    type_code = MULTIPOLYGON
    part_type = Polygon


def _close_ring(ring: np.ndarray) -> np.ndarray:
    if len(ring) < 3:
        raise ValueError("ring needs >= 3 points")
    if not np.array_equal(ring[0], ring[-1]):
        ring = np.vstack([ring, ring[:1]])
    return ring


def _ring_area(ring: np.ndarray) -> float:
    x, y = ring[:, 0], ring[:, 1]
    return float(0.5 * np.sum(x[:-1] * y[1:] - x[1:] * y[:-1]))


def box(xmin: float, ymin: float, xmax: float, ymax: float) -> Polygon:
    """Axis-aligned box polygon (the BBOX query literal)."""
    return Polygon(
        [(xmin, ymin), (xmax, ymin), (xmax, ymax), (xmin, ymax), (xmin, ymin)]
    )


# ---------------------------------------------------------------------------
# WKT codec
# ---------------------------------------------------------------------------


def _fmt_coord(c) -> str:
    def num(v: float) -> str:
        s = f"{v:.10f}".rstrip("0").rstrip(".")
        return s if s not in ("-0", "") else "0"

    return f"{num(c[0])} {num(c[1])}"


def _fmt_ring(ring: np.ndarray) -> str:
    return "(" + ", ".join(_fmt_coord(c) for c in ring) + ")"


def to_wkt(g: Geometry) -> str:
    """Serialize to WKT. Mirrors JTS WKTWriter output shape."""
    if isinstance(g, Point):
        return f"POINT ({_fmt_coord((g.x, g.y))})"
    if isinstance(g, LineString):
        return f"LINESTRING {_fmt_ring(g.coords)}"
    if isinstance(g, Polygon):
        rings = ", ".join(_fmt_ring(r) for r in [g.shell] + g.holes)
        return f"POLYGON ({rings})"
    if isinstance(g, MultiPoint):
        return "MULTIPOINT (" + ", ".join(f"({_fmt_coord((p.x, p.y))})" for p in g.parts) + ")"
    if isinstance(g, MultiLineString):
        return "MULTILINESTRING (" + ", ".join(_fmt_ring(p.coords) for p in g.parts) + ")"
    if isinstance(g, MultiPolygon):
        polys = ", ".join(
            "(" + ", ".join(_fmt_ring(r) for r in [p.shell] + p.holes) + ")" for p in g.parts
        )
        return f"MULTIPOLYGON ({polys})"
    raise ValueError(f"cannot serialize {type(g)}")


class _WktParser:
    """Recursive-descent WKT parser (POINT/LINESTRING/POLYGON/MULTI*)."""

    def __init__(self, text: str):
        self.text = text
        self.pos = 0

    def _skip_ws(self):
        while self.pos < len(self.text) and self.text[self.pos].isspace():
            self.pos += 1

    def _expect(self, ch: str):
        self._skip_ws()
        if self.pos >= len(self.text) or self.text[self.pos] != ch:
            raise ValueError(f"expected {ch!r} at {self.pos} in {self.text!r}")
        self.pos += 1

    def _peek(self) -> str:
        self._skip_ws()
        return self.text[self.pos] if self.pos < len(self.text) else ""

    def _word(self) -> str:
        self._skip_ws()
        start = self.pos
        while self.pos < len(self.text) and (self.text[self.pos].isalpha()):
            self.pos += 1
        return self.text[start : self.pos].upper()

    def _number(self) -> float:
        self._skip_ws()
        start = self.pos
        while self.pos < len(self.text) and self.text[self.pos] not in " ,()\t\n":
            self.pos += 1
        return float(self.text[start : self.pos])

    def _coord(self) -> tuple[float, float]:
        x = self._number()
        y = self._number()
        return (x, y)

    def _coord_list(self) -> np.ndarray:
        self._expect("(")
        out = [self._coord()]
        while self._peek() == ",":
            self._expect(",")
            out.append(self._coord())
        self._expect(")")
        return np.array(out, dtype=np.float64)

    def _ring_list(self) -> list[np.ndarray]:
        self._expect("(")
        rings = [self._coord_list()]
        while self._peek() == ",":
            self._expect(",")
            rings.append(self._coord_list())
        self._expect(")")
        return rings

    def parse(self) -> Geometry:
        word = self._word()
        if word not in TYPE_CODES:
            raise ValueError(f"unknown WKT type {word!r}")
        nxt = self._word()
        if nxt == "EMPTY":
            raise ValueError(f"EMPTY {word} not supported")
        if nxt:
            raise ValueError(f"unexpected token {nxt!r}")
        if word == "POINT":
            self._expect("(")
            x, y = self._coord()
            self._expect(")")
            return Point(x, y)
        if word == "LINESTRING":
            return LineString(self._coord_list())
        if word == "POLYGON":
            rings = self._ring_list()
            return Polygon(rings[0], rings[1:])
        if word == "MULTIPOINT":
            self._expect("(")
            pts = []
            while True:
                if self._peek() == "(":
                    self._expect("(")
                    pts.append(Point(*self._coord()))
                    self._expect(")")
                else:
                    pts.append(Point(*self._coord()))
                if self._peek() == ",":
                    self._expect(",")
                else:
                    break
            self._expect(")")
            return MultiPoint(pts)
        if word == "MULTILINESTRING":
            return MultiLineString([LineString(c) for c in self._ring_list()])
        # MULTIPOLYGON
        self._expect("(")
        polys = []
        while True:
            rings = self._ring_list()
            polys.append(Polygon(rings[0], rings[1:]))
            if self._peek() == ",":
                self._expect(",")
            else:
                break
        self._expect(")")
        return MultiPolygon(polys)


def from_wkt(text: str) -> Geometry:
    p = _WktParser(text.strip())
    g = p.parse()
    p._skip_ws()
    if p.pos != len(p.text):
        raise ValueError(f"trailing content in WKT: {p.text[p.pos:]!r}")
    return g


# ---------------------------------------------------------------------------
# WKB codec (little-endian, 2-D) — interop format, reference WkbSerialization
# ---------------------------------------------------------------------------


def to_wkb(g: Geometry) -> bytes:
    import struct

    def header(code: int) -> bytes:
        return struct.pack("<BI", 1, code)

    def pts(a: np.ndarray) -> bytes:
        return struct.pack("<I", len(a)) + a.astype("<f8").tobytes()

    if isinstance(g, Point):
        return header(POINT) + struct.pack("<dd", g.x, g.y)
    if isinstance(g, LineString):
        return header(LINESTRING) + pts(g.coords)
    if isinstance(g, Polygon):
        rings = [g.shell] + g.holes
        return header(POLYGON) + struct.pack("<I", len(rings)) + b"".join(pts(r) for r in rings)
    if isinstance(g, (MultiPoint, MultiLineString, MultiPolygon)):
        return (
            header(g.type_code)
            + np.uint32(len(g.parts)).tobytes()
            + b"".join(to_wkb(p) for p in g.parts)
        )
    raise ValueError(f"cannot serialize {type(g)}")


def from_wkb(data: bytes) -> Geometry:
    g, _ = _read_wkb(memoryview(data), 0)
    return g


def _read_wkb(buf: memoryview, pos: int) -> tuple[Geometry, int]:
    import struct

    byte_order = buf[pos]
    endian = "<" if byte_order == 1 else ">"
    (code,) = struct.unpack_from(endian + "I", buf, pos + 1)
    pos += 5
    if code & 0x20000000:  # EWKB SRID flag: skip the 4-byte SRID payload
        pos += 4
    if code & 0xC0000000:  # EWKB Z/M flags: 3-/4-D coords unsupported
        raise ValueError(f"unsupported WKB dimension flags in type 0x{code:x}")
    code &= 0x1FFFFFFF
    if code > MULTIPOLYGON:  # ISO WKB Z/M variants (1001, 2001, ...) too
        raise ValueError(f"unsupported WKB geometry type {code}")

    def read_pts(pos: int) -> tuple[np.ndarray, int]:
        (n,) = struct.unpack_from(endian + "I", buf, pos)
        pos += 4
        a = np.frombuffer(buf, dtype=endian + "f8", count=2 * n, offset=pos).reshape(n, 2)
        return a.copy(), pos + 16 * n

    if code == POINT:
        x, y = struct.unpack_from(endian + "dd", buf, pos)
        return Point(x, y), pos + 16
    if code == LINESTRING:
        a, pos = read_pts(pos)
        return LineString(a), pos
    if code == POLYGON:
        (nrings,) = struct.unpack_from(endian + "I", buf, pos)
        pos += 4
        rings = []
        for _ in range(nrings):
            r, pos = read_pts(pos)
            rings.append(r)
        return Polygon(rings[0], rings[1:]), pos
    if code in (MULTIPOINT, MULTILINESTRING, MULTIPOLYGON):
        (nparts,) = struct.unpack_from(endian + "I", buf, pos)
        pos += 4
        parts = []
        for _ in range(nparts):
            p, pos = _read_wkb(buf, pos)
            parts.append(p)
        cls = {MULTIPOINT: MultiPoint, MULTILINESTRING: MultiLineString, MULTIPOLYGON: MultiPolygon}
        return cls[code](parts), pos
    raise ValueError(f"unsupported WKB type {code}")


def is_rectangle(g: "Geometry") -> bool:
    """True when ``g`` is a plain axis-aligned rectangle polygon (its
    geometry IS its bbox): bbox algebra then answers spatial predicates
    against it exactly. Every edge must be axis-aligned (a closed 5-point
    "bowtie" has 2 distinct xs/ys but diagonal edges — not a rectangle)."""
    if not isinstance(g, Polygon) or g.holes:
        return False
    ring = g.shell
    if len(ring) != 5 or not np.array_equal(ring[0], ring[4]):
        return False
    xs = set(ring[:, 0].tolist())
    ys = set(ring[:, 1].tolist())
    if len(xs) != 2 or len(ys) != 2:
        return False
    dx = ring[1:, 0] != ring[:-1, 0]
    dy = ring[1:, 1] != ring[:-1, 1]
    return bool(np.all(dx ^ dy))  # each edge moves in exactly one axis


# ---------------------------------------------------------------------------
# packed columnar geometry pool (the device-facing storage layout)
# ---------------------------------------------------------------------------


def _gather_rows(src: np.ndarray, flat: np.ndarray) -> np.ndarray:
    """out[i] = src[flat[i]] through the threaded native row gather when
    the pull is big enough to matter and the indices fit u32; the
    random-row reads dominate big result pulls (PERF.md §4c)."""
    if (
        len(flat) > (1 << 16)
        and int(flat.min()) >= 0
        and int(flat.max()) < (1 << 32)
    ):
        from geomesa_tpu import native

        out = native.take_rows(src, flat)
        if out is not None:
            return out
    return src[flat]


@dataclass
class PackedGeometryColumn:
    """Arrow-style nested-list layout for a column of geometries.

    - ``coords``            f64 [total_points, 2] — every vertex
    - ``ring_offsets``      i32 [nrings + 1]  — ring r = coords[ro[r]:ro[r+1]]
    - ``part_ring_offsets`` i32 [nparts + 1]  — part p owns rings pro[p]..pro[p+1]
      (a polygon part's first ring is its shell, the rest are holes)
    - ``geom_part_offsets`` i32 [n + 1]       — geometry i owns parts gpo[i]..gpo[i+1]
    - ``types``             i8  [n]           — geometry type codes
    - ``bboxes``            f32 [n, 4]        — (xmin, ymin, xmax, ymax), widened one
      f32 ulp outward so the device prefilter never excludes a true hit

    ``bboxes`` ships to the device for the scan-kernel bbox prefilter; exact
    refinement decodes through the offsets (host) or the padded arrays from
    :func:`pad_polygons` (device point-in-polygon).
    """

    coords: np.ndarray
    ring_offsets: np.ndarray
    part_ring_offsets: np.ndarray
    geom_part_offsets: np.ndarray
    types: np.ndarray
    bboxes: np.ndarray

    def __len__(self) -> int:
        return len(self.types)

    @staticmethod
    def from_geometries(geoms: Sequence[Geometry]) -> "PackedGeometryColumn":
        coords: list[np.ndarray] = []
        ring_offsets = [0]
        part_ring_offsets = [0]
        geom_part_offsets = [0]
        types = []
        bboxes = []
        total = 0

        def add_ring(ring: np.ndarray):
            nonlocal total
            coords.append(ring)
            total += len(ring)
            ring_offsets.append(total)

        def add_part(rings: list[np.ndarray]):
            for r in rings:
                add_ring(r)
            part_ring_offsets.append(part_ring_offsets[-1] + len(rings))

        for g in geoms:
            types.append(g.type_code)
            bboxes.append(g.bounds())
            if isinstance(g, Point):
                add_part([np.array([[g.x, g.y]])])
            elif isinstance(g, LineString):
                add_part([g.coords])
            elif isinstance(g, Polygon):
                add_part([g.shell] + g.holes)
            elif isinstance(g, (MultiPoint, MultiLineString, MultiPolygon)):
                for p in g.parts:
                    if isinstance(p, Point):
                        add_part([np.array([[p.x, p.y]])])
                    elif isinstance(p, LineString):
                        add_part([p.coords])
                    else:
                        add_part([p.shell] + p.holes)
            else:
                raise ValueError(f"cannot pack {type(g)}")
            geom_part_offsets.append(len(part_ring_offsets) - 1)

        b = np.array(bboxes, dtype=np.float64).reshape(len(types), 4)
        lo = np.nextafter(b[:, :2].astype(np.float32), -np.inf)
        hi = np.nextafter(b[:, 2:].astype(np.float32), np.inf)
        return PackedGeometryColumn(
            coords=np.concatenate(coords, axis=0) if coords else np.zeros((0, 2)),
            ring_offsets=np.array(ring_offsets, dtype=np.int32),
            part_ring_offsets=np.array(part_ring_offsets, dtype=np.int32),
            geom_part_offsets=np.array(geom_part_offsets, dtype=np.int32),
            types=np.array(types, dtype=np.int8),
            bboxes=np.concatenate([lo, hi], axis=1).astype(np.float32),
        )

    @staticmethod
    def from_boxes(xmin, ymin, xmax, ymax) -> "PackedGeometryColumn":
        """Vectorized bulk constructor for n axis-aligned rectangle
        polygons (building-footprint-style ingest): 5 CCW vertices each,
        built with numpy broadcasting — no per-row Geometry objects."""
        xmin = np.asarray(xmin, dtype=np.float64)
        ymin = np.asarray(ymin, dtype=np.float64)
        xmax = np.asarray(xmax, dtype=np.float64)
        ymax = np.asarray(ymax, dtype=np.float64)
        n = len(xmin)
        coords = np.empty((n, 5, 2), dtype=np.float64)
        coords[:, 0, 0] = xmin; coords[:, 0, 1] = ymin
        coords[:, 1, 0] = xmax; coords[:, 1, 1] = ymin
        coords[:, 2, 0] = xmax; coords[:, 2, 1] = ymax
        coords[:, 3, 0] = xmin; coords[:, 3, 1] = ymax
        coords[:, 4, 0] = xmin; coords[:, 4, 1] = ymin
        b = np.stack([xmin, ymin, xmax, ymax], axis=1)
        lo = np.nextafter(b[:, :2].astype(np.float32), -np.inf)
        hi = np.nextafter(b[:, 2:].astype(np.float32), np.inf)
        idx = np.arange(n + 1, dtype=np.int32)
        col = PackedGeometryColumn(
            coords=coords.reshape(-1, 2),
            ring_offsets=idx * 5,
            part_ring_offsets=idx,
            geom_part_offsets=idx,
            types=np.full(n, POLYGON, dtype=np.int8),
            bboxes=np.concatenate([lo, hi], axis=1).astype(np.float32),
        )
        # every row is a rectangle by construction: seed the box_info
        # cache (exact f64 bounds) so queries never pay the O(n) lazy
        # rectangle detection on this column or its take() descendants,
        # and mark the uniform 5-vertex layout so take() can use one
        # width-10 row gather instead of nested offset expansion
        col._box_info = (np.ones(n, dtype=bool), b.copy())
        col._uniform_rect = True
        return col

    def box_info(self) -> tuple[np.ndarray, np.ndarray]:
        """(mask [n] bool, bounds [n, 4] f64): which geometries are plain
        axis-aligned rectangles (their geometry IS their bbox) and their
        exact f64 bounds. For those rows, bbox algebra answers spatial
        predicates exactly — the vectorized fast tier that keeps per-row
        Python refinement off box-shaped features (footprints, tiles,
        gridded extents). Computed once per column and cached."""
        cached = getattr(self, "_box_info", None)
        if cached is not None:
            return cached
        n = len(self)
        bounds = np.full((n, 4), np.nan)
        mask = self.types == POLYGON
        # every geometry owns >= 1 part and every part >= 1 ring, so the
        # first-part / first-ring lookups below are always in range
        mask &= np.diff(self.geom_part_offsets) == 1
        first_part = self.geom_part_offsets[:-1].astype(np.int64)
        mask &= np.diff(self.part_ring_offsets)[first_part] == 1
        first_ring = self.part_ring_offsets[first_part].astype(np.int64)
        mask &= np.diff(self.ring_offsets)[first_ring] == 5
        idx = np.flatnonzero(mask)
        if len(idx):
            starts = self.ring_offsets[first_ring[idx]].astype(np.int64)
            pts = self.coords[starts[:, None] + np.arange(5)]  # [k, 5, 2]
            x0 = pts[..., 0].min(axis=1)
            x1 = pts[..., 0].max(axis=1)
            y0 = pts[..., 1].min(axis=1)
            y1 = pts[..., 1].max(axis=1)
            ok = (pts[:, 0] == pts[:, 4]).all(axis=1)  # closed ring
            # every vertex on a corner, and all four corners present
            on_x = (pts[..., 0] == x0[:, None]) | (pts[..., 0] == x1[:, None])
            on_y = (pts[..., 1] == y0[:, None]) | (pts[..., 1] == y1[:, None])
            ok &= (on_x & on_y).all(axis=1)
            # every edge axis-aligned (excludes corner-ordered "bowties",
            # whose diagonal edges make the interior smaller than the bbox)
            dx = pts[:, 1:, 0] != pts[:, :-1, 0]
            dy = pts[:, 1:, 1] != pts[:, :-1, 1]
            ok &= (dx ^ dy).all(axis=1)
            for cx, cy in ((x0, y0), (x1, y0), (x1, y1), (x0, y1)):
                ok &= (
                    (pts[..., 0] == cx[:, None]) & (pts[..., 1] == cy[:, None])
                ).any(axis=1)
            mask[idx[~ok]] = False
            keep = idx[ok]
            bounds[keep, 0] = x0[ok]
            bounds[keep, 1] = y0[ok]
            bounds[keep, 2] = x1[ok]
            bounds[keep, 3] = y1[ok]
        self._box_info = (mask, bounds)
        return self._box_info

    # -- unpacking -------------------------------------------------------
    def _ring(self, r: int) -> np.ndarray:
        return self.coords[self.ring_offsets[r] : self.ring_offsets[r + 1]]

    def _part_rings(self, p: int) -> list[np.ndarray]:
        r0, r1 = int(self.part_ring_offsets[p]), int(self.part_ring_offsets[p + 1])
        return [self._ring(r) for r in range(r0, r1)]

    def geometry(self, i: int) -> Geometry:
        code = int(self.types[i])
        p0, p1 = int(self.geom_part_offsets[i]), int(self.geom_part_offsets[i + 1])
        if code == POINT:
            c = self._part_rings(p0)[0]
            return Point(c[0, 0], c[0, 1])
        if code == LINESTRING:
            return LineString(self._part_rings(p0)[0])
        if code == POLYGON:
            rings = self._part_rings(p0)
            return Polygon(rings[0], rings[1:])
        if code == MULTIPOINT:
            return MultiPoint(
                [Point(*self._part_rings(p)[0][0]) for p in range(p0, p1)]
            )
        if code == MULTILINESTRING:
            return MultiLineString(
                [LineString(self._part_rings(p)[0]) for p in range(p0, p1)]
            )
        if code == MULTIPOLYGON:
            polys = []
            for p in range(p0, p1):
                rings = self._part_rings(p)
                polys.append(Polygon(rings[0], rings[1:]))
            return MultiPolygon(polys)
        raise ValueError(f"bad type code {code}")

    def geometries(self) -> list[Geometry]:
        return [self.geometry(i) for i in range(len(self))]

    def take(self, idx: np.ndarray) -> "PackedGeometryColumn":
        """Subset by geometry indices (used when gathering query results).

        Pure array surgery — slices the nested offsets without
        materializing host geometry objects (this runs on every extent
        query's result gather).
        """
        idx = np.asarray(idx, dtype=np.int64)

        if getattr(self, "_uniform_rect", False):
            return self._take_uniform_rect(idx)

        def expand(starts, ends):
            """Concatenate aranges [starts[i], ends[i]) -> flat index list."""
            lens = ends - starts
            if len(lens) == 0 or lens.sum() == 0:
                return np.zeros(0, dtype=np.int64), np.zeros(1, dtype=np.int32)
            flat = np.repeat(starts - np.concatenate([[0], np.cumsum(lens)[:-1]]), lens) + np.arange(lens.sum())
            offsets = np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)
            return flat, offsets

        p_flat, gpo = expand(
            self.geom_part_offsets[idx].astype(np.int64),
            self.geom_part_offsets[idx + 1].astype(np.int64),
        )
        r_flat, pro = expand(
            self.part_ring_offsets[p_flat].astype(np.int64),
            self.part_ring_offsets[p_flat + 1].astype(np.int64),
        )
        c_flat, ro = expand(
            self.ring_offsets[r_flat].astype(np.int64),
            self.ring_offsets[r_flat + 1].astype(np.int64),
        )

        rows = _gather_rows
        col = PackedGeometryColumn(
            coords=rows(self.coords, c_flat),
            ring_offsets=ro,
            part_ring_offsets=pro,
            geom_part_offsets=gpo,
            types=self.types[idx],
            bboxes=rows(self.bboxes, idx),
        )
        cached = getattr(self, "_box_info", None)
        if cached is not None:  # rectangle classification survives a subset
            col._box_info = (cached[0][idx], rows(cached[1], idx))
        return col

    def _take_uniform_rect(self, idx: np.ndarray) -> "PackedGeometryColumn":
        """take() fast path for from_boxes columns: every geometry is one
        5-vertex ring, so the subset is a single [n, 10] row gather plus
        arange offsets — ~5x fewer latency-bound lookups than the generic
        nested expansion."""
        rows = _gather_rows
        n = len(idx)
        coords10 = rows(
            np.ascontiguousarray(self.coords).reshape(len(self), 10), idx
        )
        off = np.arange(n + 1, dtype=np.int32)
        col = PackedGeometryColumn(
            coords=coords10.reshape(-1, 2),
            ring_offsets=off * 5,
            part_ring_offsets=off,
            geom_part_offsets=off,
            types=self.types[idx],
            bboxes=rows(self.bboxes, idx),
        )
        cached = getattr(self, "_box_info", None)
        if cached is not None:
            col._box_info = (cached[0][idx], rows(cached[1], idx))
        col._uniform_rect = True
        return col

    @staticmethod
    def concat(cols: Sequence["PackedGeometryColumn"]) -> "PackedGeometryColumn":
        """Concatenate columns by shifting the nested offset arrays."""
        cols = list(cols)
        if len(cols) == 1:
            return cols[0]

        def stack_offsets(arrays, shifts):
            out = [arrays[0]]
            for a, s in zip(arrays[1:], shifts[1:]):
                out.append(a[1:].astype(np.int64) + s)
            return np.concatenate(out).astype(np.int32)

        coord_shift = np.concatenate([[0], np.cumsum([len(c.coords) for c in cols])])
        ring_shift = np.concatenate(
            [[0], np.cumsum([len(c.ring_offsets) - 1 for c in cols])]
        )
        part_shift = np.concatenate(
            [[0], np.cumsum([len(c.part_ring_offsets) - 1 for c in cols])]
        )
        out = PackedGeometryColumn(
            coords=np.concatenate([c.coords for c in cols], axis=0),
            ring_offsets=stack_offsets([c.ring_offsets for c in cols], coord_shift),
            part_ring_offsets=stack_offsets(
                [c.part_ring_offsets for c in cols], ring_shift
            ),
            geom_part_offsets=stack_offsets(
                [c.geom_part_offsets for c in cols], part_shift
            ),
            types=np.concatenate([c.types for c in cols]),
            bboxes=np.concatenate([c.bboxes for c in cols], axis=0),
        )
        caches = [getattr(c, "_box_info", None) for c in cols]
        if all(c is not None for c in caches):
            out._box_info = (
                np.concatenate([c[0] for c in caches]),
                np.concatenate([c[1] for c in caches], axis=0),
            )
        if all(getattr(c, "_uniform_rect", False) for c in cols):
            out._uniform_rect = True
        return out


def pad_polygon(poly: "Polygon | MultiPolygon", max_verts: int):
    """Pad a (multi)polygon into fixed-shape arrays for device kernels.

    Returns (verts f32 [max_verts, 2], n int32, ring_id int32 [max_verts]):
    all rings (shells and holes, every part) are concatenated; ``ring_id``
    marks which ring each *edge start* vertex belongs to so the device
    ray-cast never counts the closing segment between different rings.
    Even-odd crossing counting makes holes subtract automatically.
    """
    rings: list[np.ndarray] = []
    if isinstance(poly, Polygon):
        rings = [poly.shell] + poly.holes
    else:
        for p in poly.parts:
            rings += [p.shell] + p.holes
    verts = np.concatenate(rings, axis=0)
    if len(verts) > max_verts:
        raise ValueError(f"polygon has {len(verts)} verts > cap {max_verts}")
    ring_id = np.concatenate([np.full(len(r), i) for i, r in enumerate(rings)])
    out_v = np.zeros((max_verts, 2), dtype=np.float32)
    out_r = np.full(max_verts, -1, dtype=np.int32)
    out_v[: len(verts)] = verts.astype(np.float32)
    out_r[: len(verts)] = ring_id
    return out_v, np.int32(len(verts)), out_r


# ---------------------------------------------------------------------------
# predicate math (vectorized numpy; jnp twins live in geomesa_tpu.sql.stfuncs)
# ---------------------------------------------------------------------------


def bbox_intersects(a, b) -> np.ndarray:
    """Axis-aligned box overlap; a, b = (xmin, ymin, xmax, ymax) arrays."""
    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    return (
        (a[..., 0] <= b[..., 2])
        & (a[..., 2] >= b[..., 0])
        & (a[..., 1] <= b[..., 3])
        & (a[..., 3] >= b[..., 1])
    )


def points_in_ring(px, py, ring: np.ndarray) -> np.ndarray:
    """Even-odd ray-cast crossing parity of points against one ring.

    Vectorized over points. Standard construction: for each edge (x1,y1) ->
    (x2,y2), a rightward horizontal ray from (px, py) crosses it iff the edge
    spans py half-open in y and the intersection x exceeds px.
    """
    px = np.asarray(px, dtype=np.float64)
    py = np.asarray(py, dtype=np.float64)
    x1, y1 = ring[:-1, 0], ring[:-1, 1]
    x2, y2 = ring[1:, 0], ring[1:, 1]
    # [n_points, n_edges]
    pyc = py[..., None]
    pxc = px[..., None]
    spans = (y1 <= pyc) != (y2 <= pyc)
    with np.errstate(divide="ignore", invalid="ignore"):
        t = (pyc - y1) / np.where(y2 == y1, np.inf, y2 - y1)
        xi = x1 + t * (x2 - x1)
    crossings = spans & (xi > pxc)
    return crossings.sum(axis=-1) % 2 == 1


def points_in_polygon(px, py, poly: "Polygon | MultiPolygon") -> np.ndarray:
    """Point-in-polygon with holes via even-odd parity over all rings.

    Large batches route through the native threaded ray cast (identical
    crossing construction): the numpy path materializes an
    [n_points, n_edges] matrix, which dominates host refinement of
    polygon queries over point stores."""
    px = np.asarray(px, dtype=np.float64)
    py = np.asarray(py, dtype=np.float64)
    if px.ndim == 1 and px.shape == py.shape and len(px) > 4096:
        parts = poly.parts if isinstance(poly, MultiPolygon) else [poly]
        rings, ring_part = [], []
        for pi, p in enumerate(parts):
            for r in [p.shell, *p.holes]:
                rings.append(np.asarray(r, dtype=np.float64))
                ring_part.append(pi)
        from geomesa_tpu import native

        out = native.points_in_polygon(px, py, rings, ring_part)
        if out is not None:
            return out
    if isinstance(poly, MultiPolygon):
        out = np.zeros(np.broadcast(px, py).shape, dtype=bool)
        for p in poly.parts:
            out |= points_in_polygon(px, py, p)
        return out
    parity = points_in_ring(px, py, poly.shell)
    for h in poly.holes:
        parity ^= points_in_ring(px, py, h)
    return parity


# ---------------------------------------------------------------------------
# raster cell classification (the Raster Intervals core, arXiv 2307.01716)
# ---------------------------------------------------------------------------

RASTER_OUT = 0
RASTER_PARTIAL = 1
RASTER_FULL = 2


def classify_raster_cells(
    geom: "Polygon | MultiPolygon",
    x_edges: np.ndarray,
    y_edges: np.ndarray,
    margin: float = 0.0,
) -> np.ndarray:
    """int8 [ny, nx] cell classes of ``geom`` over an axis-aligned grid:
    cell (j, i) spans [x_edges[i], x_edges[i+1]] x [y_edges[j], y_edges[j+1]].

    CONSERVATIVE by construction, which is what makes raster shortcuts
    exact: a cell is RASTER_FULL only when the cell rectangle EXPANDED by
    ``margin`` lies entirely inside the polygon, RASTER_OUT only when the
    expanded rectangle misses the polygon entirely, and RASTER_PARTIAL
    otherwise — so any point within ``margin`` of a full (out) cell is a
    guaranteed f64 hit (miss), absorbing stored-f32 coordinate rounding
    and the kernel's f32 cell arithmetic. Construction: every ring edge is
    rasterized with a margin-expanded column sweep (cells its clipped
    y-span touches become PARTIAL — a superset of boundary cells, which is
    always safe); every remaining cell avoids the boundary entirely, so
    its center's even-odd parity classifies the whole cell.
    """
    nx, ny = len(x_edges) - 1, len(y_edges) - 1
    part = np.zeros((ny, nx), dtype=bool)
    for ring in _rings_of(geom):
        p1, p2 = _ring_edges(ring)
        for (x1, y1), (x2, y2) in zip(p1.tolist(), p2.tolist()):
            lo_x, hi_x = min(x1, x2) - margin, max(x1, x2) + margin
            if hi_x < x_edges[0] or lo_x > x_edges[-1]:
                continue
            c0 = max(int(np.searchsorted(x_edges, lo_x, side="right")) - 1, 0)
            c1 = min(int(np.searchsorted(x_edges, hi_x, side="right")) - 1, nx - 1)
            cols = np.arange(c0, c1 + 1)
            sl_lo = x_edges[cols] - margin
            sl_hi = x_edges[cols + 1] + margin
            dx, dy = x2 - x1, y2 - y1
            if dx == 0.0:
                y_a = np.full(len(cols), min(y1, y2))
                y_b = np.full(len(cols), max(y1, y2))
            else:
                ta = np.clip((sl_lo - x1) / dx, 0.0, 1.0)
                tb = np.clip((sl_hi - x1) / dx, 0.0, 1.0)
                y_a = y1 + np.minimum(ta, tb) * dy
                y_b = y1 + np.maximum(ta, tb) * dy
                if dy < 0:
                    y_a, y_b = y_b, y_a
            r0 = np.clip(
                np.searchsorted(y_edges, y_a - margin, side="right") - 1, 0, ny - 1
            )
            r1 = np.clip(
                np.searchsorted(y_edges, y_b + margin, side="right") - 1, 0, ny - 1
            )
            for i, a, b in zip(cols.tolist(), r0.tolist(), r1.tolist()):
                part[a : b + 1, i] = True
    cls = np.zeros((ny, nx), dtype=np.int8)
    cls[part] = RASTER_PARTIAL
    jj, ii = np.nonzero(~part)
    if len(jj):
        cxs = 0.5 * (x_edges[ii] + x_edges[ii + 1])
        cys = 0.5 * (y_edges[jj] + y_edges[jj + 1])
        inside = points_in_polygon(cxs, cys, geom)
        cls[jj[inside], ii[inside]] = RASTER_FULL
    return cls


def _orient(ax, ay, bx, by, cx, cy):
    """Sign of the cross product (b - a) x (c - a): +1 CCW, -1 CW, 0 collinear."""
    return np.sign((bx - ax) * (cy - ay) - (by - ay) * (cx - ax))


def segments_intersect(a1, a2, b1, b2) -> np.ndarray:
    """Proper-or-touching segment intersection test, vectorized.

    a1/a2/b1/b2: [..., 2] arrays. Standard orientation construction
    including the collinear-overlap cases.
    """
    a1 = np.asarray(a1, dtype=np.float64)
    a2 = np.asarray(a2, dtype=np.float64)
    b1 = np.asarray(b1, dtype=np.float64)
    b2 = np.asarray(b2, dtype=np.float64)
    d1 = _orient(b1[..., 0], b1[..., 1], b2[..., 0], b2[..., 1], a1[..., 0], a1[..., 1])
    d2 = _orient(b1[..., 0], b1[..., 1], b2[..., 0], b2[..., 1], a2[..., 0], a2[..., 1])
    d3 = _orient(a1[..., 0], a1[..., 1], a2[..., 0], a2[..., 1], b1[..., 0], b1[..., 1])
    d4 = _orient(a1[..., 0], a1[..., 1], a2[..., 0], a2[..., 1], b2[..., 0], b2[..., 1])
    proper = (d1 * d2 < 0) & (d3 * d4 < 0)

    def on_seg(px, py, qx, qy, rx, ry):
        """r collinear with p-q and within its bbox."""
        return (
            (np.minimum(px, qx) <= rx)
            & (rx <= np.maximum(px, qx))
            & (np.minimum(py, qy) <= ry)
            & (ry <= np.maximum(py, qy))
        )

    touch = (
        ((d1 == 0) & on_seg(b1[..., 0], b1[..., 1], b2[..., 0], b2[..., 1], a1[..., 0], a1[..., 1]))
        | ((d2 == 0) & on_seg(b1[..., 0], b1[..., 1], b2[..., 0], b2[..., 1], a2[..., 0], a2[..., 1]))
        | ((d3 == 0) & on_seg(a1[..., 0], a1[..., 1], a2[..., 0], a2[..., 1], b1[..., 0], b1[..., 1]))
        | ((d4 == 0) & on_seg(a1[..., 0], a1[..., 1], a2[..., 0], a2[..., 1], b2[..., 0], b2[..., 1]))
    )
    return proper | touch


def _ring_edges(ring: np.ndarray):
    return ring[:-1], ring[1:]


def _rings_of(geom: Geometry) -> list[np.ndarray]:
    if isinstance(geom, Polygon):
        return [geom.shell] + geom.holes
    if isinstance(geom, LineString):
        return [geom.coords]
    if isinstance(geom, (MultiPolygon, MultiLineString)):
        out = []
        for p in geom.parts:
            out += _rings_of(p)
        return out
    raise ValueError(f"no rings: {type(geom)}")


def _any_edge_intersection(ga: Geometry, gb: Geometry) -> bool:
    for ra in _rings_of(ga):
        a1, a2 = _ring_edges(ra)
        for rb in _rings_of(gb):
            b1, b2 = _ring_edges(rb)
            # [na, nb] cross test
            hit = segments_intersect(
                a1[:, None, :], a2[:, None, :], b1[None, :, :], b2[None, :, :]
            )
            if hit.any():
                return True
    return False


def _first_point(g: Geometry) -> tuple[float, float]:
    if isinstance(g, Point):
        return g.x, g.y
    if isinstance(g, LineString):
        return float(g.coords[0, 0]), float(g.coords[0, 1])
    if isinstance(g, Polygon):
        return float(g.shell[0, 0]), float(g.shell[0, 1])
    return _first_point(g.parts[0])


def intersects(a: Geometry, b: Geometry) -> bool:
    """Exact geometry intersection (the host twin of the device refine).

    Construction: bbox reject, then point-containment either way, then any
    edge-pair intersection. Matches JTS `intersects` semantics (boundaries
    touching counts) for the supported types.
    """
    if not bool(bbox_intersects(np.array(a.bounds()), np.array(b.bounds()))):
        return False
    for g1, g2 in ((a, b), (b, a)):
        if isinstance(g1, Point):
            return _geom_covers_point(g2, g1.x, g1.y)
        if isinstance(g1, MultiPoint):
            return any(_geom_covers_point(g2, p.x, p.y) for p in g1.parts)
    # both have extent: containment either way, else edge intersection
    ax, ay = _first_point(a)
    bx, by = _first_point(b)
    if isinstance(b, (Polygon, MultiPolygon)) and bool(points_in_polygon(ax, ay, b)):
        return True
    if isinstance(a, (Polygon, MultiPolygon)) and bool(points_in_polygon(bx, by, a)):
        return True
    return _any_edge_intersection(a, b)


def _geom_covers_point(g: Geometry, x: float, y: float) -> bool:
    if isinstance(g, Point):
        return g.x == x and g.y == y
    if isinstance(g, MultiPoint):
        return any(p.x == x and p.y == y for p in g.parts)
    if isinstance(g, (Polygon, MultiPolygon)):
        if bool(points_in_polygon(x, y, g)):
            return True
        # boundary counts as intersecting
        return _point_on_rings(g, x, y)
    if isinstance(g, (LineString, MultiLineString)):
        return _point_on_rings(g, x, y)
    raise ValueError(type(g))


def points_on_boundary(px, py, g: Geometry) -> np.ndarray:
    """Vectorized-over-points sibling of ``_point_on_rings``: which (px,
    py) lie exactly on a ring edge of ``g`` (same _orient collinearity +
    edge-bbox test, looped over the few edges instead of the many
    points)."""
    px = np.asarray(px, dtype=np.float64)
    py = np.asarray(py, dtype=np.float64)
    out = np.zeros(len(px), dtype=bool)
    for ring in _rings_of(g):
        p1, p2 = _ring_edges(ring)
        for (x1, y1), (x2, y2) in zip(p1.tolist(), p2.tolist()):
            d = _orient(x1, y1, x2, y2, px, py)
            out |= (
                (d == 0)
                & (min(x1, x2) <= px) & (px <= max(x1, x2))
                & (min(y1, y2) <= py) & (py <= max(y1, y2))
            )
    return out


def _point_on_rings(g: Geometry, x: float, y: float) -> bool:
    for ring in _rings_of(g):
        p1, p2 = _ring_edges(ring)
        d = _orient(p1[:, 0], p1[:, 1], p2[:, 0], p2[:, 1], x, y)
        on = (
            (d == 0)
            & (np.minimum(p1[:, 0], p2[:, 0]) <= x)
            & (x <= np.maximum(p1[:, 0], p2[:, 0]))
            & (np.minimum(p1[:, 1], p2[:, 1]) <= y)
            & (y <= np.maximum(p1[:, 1], p2[:, 1]))
        )
        if on.any():
            return True
    return False


def contains(a: Geometry, b: Geometry) -> bool:
    """Does polygonal `a` contain `b`? (all of b's vertices inside a, no
    boundary crossing, and no hole of `a` lying inside b — the JTS
    `contains` for the cases the query path needs: polygon contains
    point/line/polygon)."""
    if not isinstance(a, (Polygon, MultiPolygon)):
        raise ValueError("contains() requires a polygonal left operand")
    if isinstance(b, Point):
        return bool(points_in_polygon(b.x, b.y, a))
    if isinstance(b, MultiPoint):
        return all(bool(points_in_polygon(p.x, p.y, a)) for p in b.parts)
    verts = np.concatenate(_rings_of(b), axis=0)
    if not bool(points_in_polygon(verts[:, 0], verts[:, 1], a).all()):
        return False
    if _any_edge_intersection(a, b):
        return False
    # a hole of `a` strictly inside b excludes part of b's interior even
    # though no vertex of b touches it and no edges cross
    if isinstance(b, (Polygon, MultiPolygon)):
        holes = (
            a.holes
            if isinstance(a, Polygon)
            else [h for p in a.parts for h in p.holes]
        )
        for h in holes:
            if bool(points_in_polygon(h[:-1, 0], h[:-1, 1], b).any()):
                return False
    return True


def distance(a: Geometry, b: Geometry) -> float:
    """Euclidean (planar degrees) distance between two geometries."""
    if isinstance(a, Point) and isinstance(b, Point):
        return float(np.hypot(a.x - b.x, a.y - b.y))
    if isinstance(a, Point):
        return _point_geom_distance(a.x, a.y, b)
    if isinstance(b, Point):
        return _point_geom_distance(b.x, b.y, a)
    if intersects(a, b):
        return 0.0
    va = np.concatenate(_rings_of(a), axis=0)
    best = np.inf
    for ring in _rings_of(b):
        p1, p2 = _ring_edges(ring)
        for v in va:
            best = min(best, float(_point_segments_distance(v[0], v[1], p1, p2).min()))
    vb = np.concatenate(_rings_of(b), axis=0)
    for ring in _rings_of(a):
        p1, p2 = _ring_edges(ring)
        for v in vb:
            best = min(best, float(_point_segments_distance(v[0], v[1], p1, p2).min()))
    return best


def _point_segments_distance(x, y, p1: np.ndarray, p2: np.ndarray) -> np.ndarray:
    """Distance from (x, y) to each segment p1[i] -> p2[i]."""
    d = p2 - p1
    len2 = (d**2).sum(axis=1)
    ap = np.stack([x - p1[:, 0], y - p1[:, 1]], axis=1)
    t = np.clip((ap * d).sum(axis=1) / np.where(len2 == 0, 1, len2), 0.0, 1.0)
    proj = p1 + t[:, None] * d
    return np.hypot(x - proj[:, 0], y - proj[:, 1])


def _point_geom_distance(x: float, y: float, g: Geometry) -> float:
    if isinstance(g, Point):
        return float(np.hypot(x - g.x, y - g.y))
    if isinstance(g, MultiPoint):
        return min(float(np.hypot(x - p.x, y - p.y)) for p in g.parts)
    if isinstance(g, (Polygon, MultiPolygon)) and bool(points_in_polygon(x, y, g)):
        return 0.0
    best = np.inf
    for ring in _rings_of(g):
        p1, p2 = _ring_edges(ring)
        best = min(best, float(_point_segments_distance(x, y, p1, p2).min()))
    return best
