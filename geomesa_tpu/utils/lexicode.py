"""Order-preserving u64 lexicoding of attribute values.

Reference: the attribute index lexicodes values into sortable row-key
strings (AttributeIndexKey.scala:21-70 over org.locationtech.geomesa.utils
lexicoders). The TPU redesign lexicodes into one u64 sort key — weakly
order-preserving (v1 <= v2 implies code(v1) <= code(v2)), so searchsorted
range pruning over the sorted key column is a correct superset and exact
semantics come from host refinement:

- strings: first 8 UTF-8 bytes big-endian (longer strings collide onto
  their prefix — collisions only widen the scanned span)
- signed ints: sign-bit flip
- floats: IEEE-754 total-order trick (flip sign bit for positives, all
  bits for negatives)
- dates: epoch-millis as signed ints
"""

from __future__ import annotations

import numpy as np

U64_MAX = np.uint64(0xFFFFFFFFFFFFFFFF)
SIGN = np.uint64(0x8000000000000000)


def lex_int(col) -> np.ndarray:
    c = np.asarray(col).astype(np.int64)
    return c.view(np.uint64) ^ SIGN


def lex_float(col) -> np.ndarray:
    c = np.asarray(col, dtype=np.float64)
    b = c.view(np.uint64)
    neg = (b & SIGN) != 0
    return np.where(neg, ~b, b | SIGN)


def lex_string(col, word: int = 0) -> np.ndarray:
    """u64 lexicode word ``word`` of a string column: UTF-8 bytes
    [8*word, 8*word+8) big-endian, null-padded. Word 0 is the primary
    sort key; word 1 the tie-breaking secondary (WriteKeys.sub). Byte
    order of UTF-8 == code-point order, so each word is weakly
    order-preserving even when truncation splits a multi-byte sequence."""
    c = np.asarray(col)
    n = len(c)
    if n == 0:
        return np.zeros(0, dtype=np.uint64)
    # vectorized: encode enough chars to cover the byte window (a UTF-8
    # char is >= 1 byte, so (word+1)*8 chars always cover it), then slice
    # the window from a fixed-width bytes view
    width = (word + 1) * 8
    raw = np.char.encode(c.astype(f"U{width}"), "utf-8").astype(f"S{width}")
    b = np.frombuffer(raw.tobytes(), dtype=np.uint8).reshape(n, width)
    window = b[:, word * 8 : word * 8 + 8]
    return np.ascontiguousarray(window).view(">u8")[:, 0].astype(np.uint64)


def lex_column(col, attr_type: str) -> np.ndarray:
    """Lexicode one column according to its SFT attribute type."""
    if attr_type in ("Integer", "Int", "Long", "Date"):
        return lex_int(col)
    if attr_type in ("Float", "Double"):
        return lex_float(col)
    return lex_string(col)


def lex_value(v, attr_type: str):
    """Lexicode one scalar (query bounds); None maps to the open extreme."""
    return lex_column(np.array([v]), attr_type)[0]


def bounds_to_range(lo, hi, attr_type: str) -> tuple[np.uint64, np.uint64]:
    """Inclusive [lo, hi] u64 scan range for attribute value bounds; None
    means unbounded on that side. Exclusive query bounds still map to the
    inclusive code range (string prefixes collide; refinement is exact)."""
    code_lo = np.uint64(0) if lo is None else lex_value(lo, attr_type)
    code_hi = U64_MAX if hi is None else lex_value(hi, attr_type)
    return code_lo, code_hi


# cap on secondary sort words: 7 words -> values distinct within their
# first 64 UTF-8 bytes prune exactly; longer shared prefixes only widen
# the scanned span (host refinement stays exact)
MAX_SUB_WORDS = 7


def lex_string_words(col) -> "np.ndarray | None":
    """Variable-width secondary sort words for a string column: u64 words
    1..W of the lexicode ([n, W], big-endian bytes [8, 8+8W)), where W is
    just wide enough to cover the longest encoded value (capped at
    MAX_SUB_WORDS). None when every value fits the 8-byte primary word.
    Zero-padding IS the correct order semantics: a shorter string sorts
    before any extension of it, and 0 is the pad byte."""
    c = np.asarray(col)
    n = len(c)
    if n == 0:
        return None
    enc = np.char.encode(c.astype(str), "utf-8")
    max_len = int(np.char.str_len(enc).max()) if len(enc) else 0
    n_words = min(max(0, -(-(max_len - 8) // 8)), MAX_SUB_WORDS)
    if n_words == 0:
        return None
    # ONE encode pass at the full width, then slice every 8-byte window
    # from the same bytes view (np.char.encode is per-element; repeating
    # it per word made ingest pay W+1 full-column passes)
    width = (n_words + 1) * 8
    raw = np.char.encode(c.astype(f"U{width}"), "utf-8").astype(f"S{width}")
    b = np.frombuffer(raw.tobytes(), dtype=np.uint8).reshape(n, width)
    return np.stack(
        [
            np.ascontiguousarray(b[:, 8 * (j + 1) : 8 * (j + 2)])
            .view(">u8")[:, 0]
            .astype(np.uint64)
            for j in range(n_words)
        ],
        axis=1,
    )


def bounds_sub_words(lo, hi) -> tuple[np.ndarray, np.ndarray]:
    """[MAX_SUB_WORDS] secondary-word bounds for a string range: word j of
    each bound value (zero-padded past the value's length — its exact
    key), unbounded sides at the open extremes. Tables narrow with their
    own word count; extra config words are ignored."""
    lo_w = np.zeros(MAX_SUB_WORDS, dtype=np.uint64)
    hi_w = np.full(MAX_SUB_WORDS, U64_MAX, dtype=np.uint64)
    if lo is not None:
        a = np.array([lo])
        for j in range(MAX_SUB_WORDS):
            lo_w[j] = lex_string(a, 1 + j)[0]
    if hi is not None:
        a = np.array([hi])
        for j in range(MAX_SUB_WORDS):
            hi_w[j] = lex_string(a, 1 + j)[0]
    return lo_w, hi_w
