"""Order-preserving u64 lexicoding of attribute values.

Reference: the attribute index lexicodes values into sortable row-key
strings (AttributeIndexKey.scala:21-70 over org.locationtech.geomesa.utils
lexicoders). The TPU redesign lexicodes into one u64 sort key — weakly
order-preserving (v1 <= v2 implies code(v1) <= code(v2)), so searchsorted
range pruning over the sorted key column is a correct superset and exact
semantics come from host refinement:

- strings: first 8 UTF-8 bytes big-endian (longer strings collide onto
  their prefix — collisions only widen the scanned span)
- signed ints: sign-bit flip
- floats: IEEE-754 total-order trick (flip sign bit for positives, all
  bits for negatives)
- dates: epoch-millis as signed ints
"""

from __future__ import annotations

import numpy as np

U64_MAX = np.uint64(0xFFFFFFFFFFFFFFFF)
SIGN = np.uint64(0x8000000000000000)


def lex_int(col) -> np.ndarray:
    c = np.asarray(col).astype(np.int64)
    return c.view(np.uint64) ^ SIGN


def lex_float(col) -> np.ndarray:
    c = np.asarray(col, dtype=np.float64)
    b = c.view(np.uint64)
    neg = (b & SIGN) != 0
    return np.where(neg, ~b, b | SIGN)


def lex_string(col) -> np.ndarray:
    c = np.asarray(col)
    n = len(c)
    if n == 0:
        return np.zeros(0, dtype=np.uint64)
    # vectorized: encode the first 8 chars, truncate/null-pad to an S8 view,
    # read big-endian (byte order of UTF-8 == code-point order, so the
    # result is weakly order-preserving even when truncation splits a
    # multi-byte sequence)
    raw = np.char.encode(c.astype("U8"), "utf-8").astype("S8")
    return np.frombuffer(raw.tobytes(), dtype=">u8").astype(np.uint64)


def lex_column(col, attr_type: str) -> np.ndarray:
    """Lexicode one column according to its SFT attribute type."""
    if attr_type in ("Integer", "Int", "Long", "Date"):
        return lex_int(col)
    if attr_type in ("Float", "Double"):
        return lex_float(col)
    return lex_string(col)


def lex_value(v, attr_type: str):
    """Lexicode one scalar (query bounds); None maps to the open extreme."""
    return lex_column(np.array([v]), attr_type)[0]


def bounds_to_range(lo, hi, attr_type: str) -> tuple[np.uint64, np.uint64]:
    """Inclusive [lo, hi] u64 scan range for attribute value bounds; None
    means unbounded on that side. Exclusive query bounds still map to the
    inclusive code range (string prefixes collide; refinement is exact)."""
    code_lo = np.uint64(0) if lo is None else lex_value(lo, attr_type)
    code_hi = U64_MAX if hi is None else lex_value(hi, attr_type)
    return code_lo, code_hi
