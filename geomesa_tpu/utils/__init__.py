"""Foundation utilities (the geomesa-utils analogue): BIN format, geohash,
in-memory spatial index, byte/lexicoder helpers."""
