"""In-memory bucket grid spatial index.

Reference: BucketIndex (/root/reference/geomesa-utils-parent/geomesa-utils/
src/main/scala/org/locationtech/geomesa/utils/index/BucketIndex.scala:
30-75) — a fixed grid of buckets over an envelope backing the Kafka
feature cache. Same design: O(1) insert/remove by (id, x, y), bbox query
collects the covered buckets. Extents insert into every covered bucket
(the SizeSeparatedBucketIndex case collapses to multi-bucket insertion).
"""

from __future__ import annotations

import math


class BucketIndex:
    """Grid-bucketed point/extent index keyed by feature id.

    Pure-scalar cell math: the original numpy clip/meshgrid per insert
    cost ~45 µs/row and dominated the streaming hot tier's sustained
    write rate (the per-point cell set is ONE integer) — scalar
    floor/clamp is ~20x cheaper at the single-feature granularity this
    index lives at (docs/streaming.md)."""

    def __init__(
        self,
        nx: int = 360,
        ny: int = 180,
        envelope: tuple = (-180.0, -90.0, 180.0, 90.0),
    ):
        self.nx, self.ny = nx, ny
        self.x0, self.y0, self.x1, self.y1 = (float(v) for v in envelope)
        self._fx = self.nx / (self.x1 - self.x0)
        self._fy = self.ny / (self.y1 - self.y0)
        self._buckets: dict[int, set] = {}
        self._entries: dict[object, tuple] = {}  # id -> (bbox, bucket ids)

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key) -> bool:
        return key in self._entries

    def _cells(self, bbox) -> list:
        x0, y0, x1, y1 = bbox
        i0 = min(max(math.floor((x0 - self.x0) * self._fx), 0), self.nx - 1)
        j0 = min(max(math.floor((y0 - self.y0) * self._fy), 0), self.ny - 1)
        if x1 == x0 and y1 == y0:  # points: one cell, no loop
            return [j0 * self.nx + i0]
        i1 = min(max(math.floor((x1 - self.x0) * self._fx), 0), self.nx - 1)
        j1 = min(max(math.floor((y1 - self.y0) * self._fy), 0), self.ny - 1)
        return [
            j * self.nx + i
            for j in range(j0, j1 + 1)
            for i in range(i0, i1 + 1)
        ]

    def insert(self, key, bbox) -> None:
        """Insert/replace an entry; bbox = (xmin, ymin, xmax, ymax) (a
        point's bbox is degenerate)."""
        if key in self._entries:
            self.remove(key)
        cells = self._cells(bbox)
        for c in cells:
            self._buckets.setdefault(c, set()).add(key)
        self._entries[key] = (tuple(float(v) for v in bbox), cells)

    def bulk_insert_points(self, keys, xs, ys) -> None:
        """Vectorized insert/replace of many POINT entries: one numpy
        pass computes every entry's cell and per-cell groups land in
        their bucket sets with C-level ``set.update`` slices (the
        per-entry scalar floors, allocs and set adds of :meth:`insert`
        dominated WAL replay — docs/durability.md "Replay batching").
        Later duplicates win, exactly like sequential :meth:`insert`
        calls."""
        import numpy as np

        entries, buckets = self._entries, self._buckets
        kset = set(keys)
        stale = kset & entries.keys() if entries else ()
        for k in stale:
            self.remove(k)
        if len(keys) != len(kset):
            # in-batch duplicate ids: keep only the LAST occurrence (the
            # replay batch coalesces many records; latest message wins)
            last: dict = {}
            for pos, k in enumerate(keys):
                last[k] = pos
            keep = sorted(last.values())
            keys = [keys[p] for p in keep]
            xs = np.asarray(xs, np.float64)[keep]
            ys = np.asarray(ys, np.float64)[keep]
        i = np.minimum(np.maximum(
            np.floor((np.asarray(xs, np.float64) - self.x0) * self._fx)
            .astype(np.int64), 0), self.nx - 1)
        j = np.minimum(np.maximum(
            np.floor((np.asarray(ys, np.float64) - self.y0) * self._fy)
            .astype(np.int64), 0), self.ny - 1)
        cells = j * self.nx + i
        cl = cells.tolist()
        xs_l = np.asarray(xs, np.float64).tolist()
        ys_l = np.asarray(ys, np.float64).tolist()
        entries.update(
            (k, ((x, y, x, y), [c]))
            for k, c, x, y in zip(keys, cl, xs_l, ys_l)
        )
        order = np.argsort(cells, kind="stable")
        sorted_keys = [keys[p] for p in order.tolist()]
        sc = cells[order]
        uniq, first = np.unique(sc, return_index=True)
        starts = np.append(first, len(sc)).tolist()
        for t, c in enumerate(uniq.tolist()):
            b = buckets.get(c)
            if b is None:
                b = buckets[c] = set()
            b.update(sorted_keys[starts[t] : starts[t + 1]])

    def remove(self, key) -> bool:
        entry = self._entries.pop(key, None)
        if entry is None:
            return False
        for c in entry[1]:
            b = self._buckets.get(c)
            if b is not None:
                b.discard(key)
                if not b:
                    del self._buckets[c]
        return True

    def query(self, bbox) -> list:
        """Keys whose bbox intersects the query bbox."""
        x0, y0, x1, y1 = bbox
        seen: set = set()
        out = []
        for c in self._cells(bbox):
            for key in self._buckets.get(c, ()):
                if key in seen:
                    continue
                seen.add(key)
                b = self._entries[key][0]
                if b[0] <= x1 and b[2] >= x0 and b[1] <= y1 and b[3] >= y0:
                    out.append(key)
        return out

    def keys(self):
        return self._entries.keys()
