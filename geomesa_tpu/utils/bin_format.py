"""BIN format: compact 16/24-byte track-point records.

Reference: BinaryOutputEncoder (/root/reference/geomesa-utils-parent/
geomesa-utils/src/main/scala/org/locationtech/geomesa/utils/bin/
BinaryOutputEncoder.scala + BinaryOutputCallback.scala:28-42). Wire layout
(little-endian, byte-compatible with the reference):

    [trackId i32][dtg seconds i32][lat f32][lon f32]           (16 bytes)
    [trackId i32][dtg seconds i32][lat f32][lon f32][label u64] (24 bytes)

The reference encodes one feature at a time through a callback; here whole
columns encode in one vectorized structured-array write, and decode returns
columns. Track ids are 32-bit string hashes of the track attribute
(reference uses String.hashCode of the track value; we use FNV-1a folded to
i32 — ids are opaque correlation keys, not interchange values).
"""

from __future__ import annotations

import numpy as np

RECORD = np.dtype(
    [("track", "<i4"), ("dtg", "<i4"), ("lat", "<f4"), ("lon", "<f4")]
)
RECORD_LABEL = np.dtype(
    [("track", "<i4"), ("dtg", "<i4"), ("lat", "<f4"), ("lon", "<f4"), ("label", "<u8")]
)


def track_ids(col: np.ndarray) -> np.ndarray:
    """i32 correlation ids from an arbitrary column (vectorized FNV-1a over
    the full fixed-width value, so long values sharing a prefix still get
    distinct ids)."""
    col = np.asarray(col)
    if col.dtype.kind in "iu":
        return col.astype(np.int64).astype(np.int32)
    if len(col) == 0:
        return np.zeros(0, dtype=np.int32)
    from geomesa_tpu.utils.hashing import fnv_fold

    h = fnv_fold(col)
    return (h & np.uint64(0xFFFFFFFF)).astype(np.uint32).view(np.int32)


def label_u64(col: np.ndarray) -> np.ndarray:
    """u64 labels: first 8 bytes of the UTF-8 value, zero-padded (reference
    Convert2ViewerFunction label semantics)."""
    col = np.asarray(col)
    if col.dtype.kind in "iu":
        return col.astype(np.uint64)
    if len(col) == 0:
        return np.zeros(0, dtype=np.uint64)
    raw = np.char.encode(col.astype("U8"), "utf-8").astype("S8")
    return np.frombuffer(raw.tobytes(), dtype="<u8").astype(np.uint64)


def encode(
    lon: np.ndarray,
    lat: np.ndarray,
    dtg_millis: np.ndarray,
    track: np.ndarray,
    label: np.ndarray | None = None,
    sort: bool = False,
) -> bytes:
    """Encode columns into concatenated BIN records."""
    n = len(lon)
    rec = np.empty(n, dtype=RECORD_LABEL if label is not None else RECORD)
    rec["track"] = track_ids(track) if track is not None else np.zeros(n, np.int32)
    rec["dtg"] = (np.asarray(dtg_millis, dtype=np.int64) // 1000).astype(np.int32)
    rec["lat"] = np.asarray(lat, dtype=np.float32)
    rec["lon"] = np.asarray(lon, dtype=np.float32)
    if label is not None:
        rec["label"] = label_u64(label)
    if sort:  # reference sorts by the 4 date bytes (BinaryOutputEncoder.DateOrdering)
        rec = rec[np.argsort(rec["dtg"], kind="stable")]
    return rec.tobytes()


def decode(data: bytes, label: bool = False) -> dict:
    """Decode concatenated BIN records back into columns."""
    rec = np.frombuffer(data, dtype=RECORD_LABEL if label else RECORD)
    out = {
        "track": rec["track"].copy(),
        "dtg_s": rec["dtg"].copy(),
        "lat": rec["lat"].copy(),
        "lon": rec["lon"].copy(),
    }
    if label:
        out["label"] = rec["label"].copy()
    return out
