"""Vectorized string/value hashing shared by sketches and the BIN codec.

The FNV-style fold runs over the full fixed-width UTF-32 view of a string
column, skipping zero (padding) words so a value hashes identically
regardless of the column's declared width (a U1 scalar probe must match
the same value observed in a U16 column).
"""

from __future__ import annotations

import numpy as np

FNV_OFFSET = 0xCBF29CE484222325
FNV_PRIME = np.uint64(0x100000001B3)


def fnv_fold(col: np.ndarray) -> np.ndarray:
    """u64 hash per element of a string (or stringable) column."""
    c = col if col.dtype.kind == "U" else col.astype(str)
    width = max(1, c.dtype.itemsize // 4)
    b = np.frombuffer(c.tobytes(), dtype=np.uint32).reshape(len(c), width).astype(np.uint64)
    h = np.full(len(c), FNV_OFFSET, dtype=np.uint64)
    for j in range(b.shape[1]):
        w = b[:, j]
        h = np.where(w != 0, (h ^ w) * FNV_PRIME, h)
    return h
