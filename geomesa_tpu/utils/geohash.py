"""Geohash codec: base-32 cell encoding of (lon, lat).

Reference: geomesa-utils geohash (/root/reference/geomesa-utils-parent/
geomesa-utils/src/main/scala/org/locationtech/geomesa/utils/geohash/
GeoHash.scala, GeohashUtils.scala) — used there for polygon decomposition
and interop. Re-derived from the public geohash construction: interleaved
lon/lat bisection bits, 5 bits per base-32 character. Vectorized over
numpy arrays; the bit interleave reuses the same Morton structure as the
Z2 curve (curve/zorder.py) — a geohash IS a z-curve prefix with a
different alphabet.
"""

from __future__ import annotations

import numpy as np

_BASE32 = "0123456789bcdefghjkmnpqrstuvwxyz"
_DECODE = {c: i for i, c in enumerate(_BASE32)}


def _interleave(x: np.ndarray, y: np.ndarray) -> np.ndarray:
    """Morton word whose MSB-first reading alternates x, y (x first).

    Z2.index(a, b) puts a's bits at even positions from bit 0; passing
    (y, x) puts x at the odd (higher) positions, so the word read from
    the top starts with x — the geohash bit order."""
    from geomesa_tpu.curve.zorder import Z2

    return Z2.index(y.astype(np.uint64), x.astype(np.uint64))


def encode(lon, lat, precision: int = 12) -> np.ndarray:
    """Geohash strings ([n] or scalar) at ``precision`` characters."""
    if not 1 <= precision <= 12:
        raise ValueError("geohash precision must be in [1, 12]")
    scalar = np.isscalar(lon)
    lon = np.atleast_1d(np.asarray(lon, dtype=np.float64))
    lat = np.atleast_1d(np.asarray(lat, dtype=np.float64))
    nbits = precision * 5
    xbits = (nbits + 1) // 2  # lon gets the extra bit at odd precisions
    ybits = nbits // 2
    xq = np.clip(
        ((lon + 180.0) / 360.0 * (1 << xbits)).astype(np.int64), 0, (1 << xbits) - 1
    ).astype(np.uint64)
    yq = np.clip(
        ((lat + 90.0) / 180.0 * (1 << ybits)).astype(np.int64), 0, (1 << ybits) - 1
    ).astype(np.uint64)
    if xbits > ybits:  # align widths: pad lat with one low zero bit
        z = _interleave(xq, yq << np.uint64(1)) >> np.uint64(1)
    else:
        z = _interleave(xq, yq)
    # z now holds nbits of alternating lon/lat from the top of nbits
    chars = np.empty((len(lon), precision), dtype="U1")
    for c in range(precision):
        shift = np.uint64(nbits - 5 * (c + 1))
        idx = ((z >> shift) & np.uint64(31)).astype(np.int64)
        chars[:, c] = np.array(list(_BASE32))[idx]
    out = np.array(["".join(row) for row in chars])
    return out[0] if scalar else out


def decode(geohash: str) -> tuple[float, float]:
    """Center (lon, lat) of a geohash cell."""
    x0, y0, x1, y1 = bbox(geohash)
    return (x0 + x1) / 2.0, (y0 + y1) / 2.0


def bbox(geohash: str) -> tuple[float, float, float, float]:
    """(lon_min, lat_min, lon_max, lat_max) of a geohash cell."""
    lon_lo, lon_hi = -180.0, 180.0
    lat_lo, lat_hi = -90.0, 90.0
    even = True  # lon bit first
    for ch in geohash.lower():
        v = _DECODE[ch]
        for b in (16, 8, 4, 2, 1):
            mid_on = v & b
            if even:
                m = (lon_lo + lon_hi) / 2.0
                if mid_on:
                    lon_lo = m
                else:
                    lon_hi = m
            else:
                m = (lat_lo + lat_hi) / 2.0
                if mid_on:
                    lat_lo = m
                else:
                    lat_hi = m
            even = not even
    return lon_lo, lat_lo, lon_hi, lat_hi


def neighbors(geohash: str) -> list[str]:
    """The 8 adjacent cells at the same precision (clipped at the poles;
    wraps across the antimeridian)."""
    x0, y0, x1, y1 = bbox(geohash)
    w, h = x1 - x0, y1 - y0
    cx, cy = (x0 + x1) / 2.0, (y0 + y1) / 2.0
    out = []
    for dy in (-h, 0.0, h):
        for dx in (-w, 0.0, w):
            if dx == 0.0 and dy == 0.0:
                continue
            ny = cy + dy
            if ny < -90.0 or ny > 90.0:
                continue
            nx = cx + dx
            if nx < -180.0:
                nx += 360.0
            elif nx > 180.0:
                nx -= 360.0
            out.append(str(encode(nx, ny, precision=len(geohash))))
    return out
