"""Stats sketches: MinMax, Histogram, Frequency (count-min), TopK, Z3Histogram.

Reference: the `geomesa-utils` stats package (/root/reference/
geomesa-utils-parent/geomesa-utils/src/main/scala/org/locationtech/geomesa/
utils/stats/ — MinMax.scala, Histogram.scala, Frequency.scala, TopK.scala,
Z3Histogram.scala, parse DSL Stat.scala:30). The reference observes one
feature at a time inside server iterators; the TPU redesign observes whole
columns with vectorized reductions and merges partial sketches with `+=`
(the collective-merge analogue: per-shard sketches psum/concat-merge into
one).
"""

from __future__ import annotations


import numpy as np

__all__ = [
    "MinMax",
    "Histogram",
    "Frequency",
    "TopK",
    "Z3Histogram",
    "CountStat",
    "DescriptiveStats",
    "Z3Frequency",
]


class CountStat:
    """Total observed count (reference CountStat)."""

    def __init__(self):
        self.count = 0

    def observe(self, col: np.ndarray) -> None:
        self.count += len(col)

    def __iadd__(self, other: "CountStat") -> "CountStat":
        self.count += other.count
        return self

    def to_json(self):
        return {"count": int(self.count)}


class MinMax:
    """Min/max bounds of one attribute (reference MinMax.scala)."""

    def __init__(self):
        self.min = None
        self.max = None
        self.count = 0

    def observe(self, col: np.ndarray) -> None:
        col = np.asarray(col)
        if len(col) == 0:
            return
        self.count += len(col)
        lo, hi = col.min(), col.max()
        self.min = lo if self.min is None else min(self.min, lo)
        self.max = hi if self.max is None else max(self.max, hi)

    def __iadd__(self, other: "MinMax") -> "MinMax":
        if other.min is not None:
            self.observe(np.array([other.min, other.max]))
            self.count += other.count - 2
        return self

    @property
    def bounds(self):
        return None if self.min is None else (self.min, self.max)

    def to_json(self):
        if self.min is None:
            return {"min": None, "max": None, "count": 0}
        return {
            "min": self.min.item() if hasattr(self.min, "item") else self.min,
            "max": self.max.item() if hasattr(self.max, "item") else self.max,
            "count": int(self.count),
        }


class Histogram:
    """Fixed-width binned counts over [lo, hi] (reference Histogram.scala:
    the planner's range-selectivity input)."""

    def __init__(self, n_bins: int, lo: float, hi: float):
        if hi <= lo:
            hi = lo + 1.0
        self.n_bins = n_bins
        self.lo = float(lo)
        self.hi = float(hi)
        self.counts = np.zeros(n_bins, dtype=np.int64)

    def observe(self, col: np.ndarray) -> None:
        col = np.asarray(col, dtype=np.float64)
        if len(col) == 0:
            return
        idx = ((col - self.lo) / (self.hi - self.lo) * self.n_bins).astype(np.int64)
        idx = np.clip(idx, 0, self.n_bins - 1)
        # bincount is ~20x np.add.at — this runs per ingest batch
        self.counts += np.bincount(idx, minlength=self.n_bins)

    def __iadd__(self, other: "Histogram") -> "Histogram":
        if (other.lo, other.hi, other.n_bins) == (self.lo, self.hi, self.n_bins):
            self.counts += other.counts
            return self
        # bounds differ across batches: rebin both into the union span
        # (reference Histogram expands via its defined bounds; here bounds
        # are data-derived per batch so the merge rebins proportionally)
        lo, hi = min(self.lo, other.lo), max(self.hi, other.hi)
        n = max(self.n_bins, other.n_bins)
        out = Histogram(n, lo, hi)
        for h in (self, other):
            w = (h.hi - h.lo) / h.n_bins
            centers = h.lo + (np.arange(h.n_bins) + 0.5) * w
            idx = np.clip(
                ((centers - lo) / (hi - lo) * n).astype(np.int64), 0, n - 1
            )
            np.add.at(out.counts, idx, h.counts)
        self.n_bins, self.lo, self.hi, self.counts = n, lo, hi, out.counts
        return self

    def estimate_range(self, lo: float, hi: float) -> float:
        """Estimated count within [lo, hi] assuming uniform intra-bin mass.
        Vectorized: hot-path callers (estimate_bbox, the kNN radius
        refinement) probe this several times per query."""
        w = (self.hi - self.lo) / self.n_bins
        edges = self.lo + np.arange(self.n_bins + 1) * w
        overlap = np.clip(
            np.minimum(hi, edges[1:]) - np.maximum(lo, edges[:-1]), 0.0, w
        )
        return float((self.counts * (overlap / w)).sum())

    def to_json(self):
        return {
            "bins": self.n_bins,
            "lo": self.lo,
            "hi": self.hi,
            "counts": self.counts.tolist(),
        }


def _cm_hashes(keys: np.ndarray, depth: int, width: int) -> np.ndarray:
    """[depth, n] multiply-shift hashes of u64 keys."""
    keys = keys.astype(np.uint64)
    salts = np.array(
        [0x9E3779B97F4A7C15, 0xBF58476D1CE4E5B9, 0x94D049BB133111EB, 0xD6E8FEB86659FD93],
        dtype=np.uint64,
    )[:depth, None]
    h = keys[None, :] * salts
    h ^= h >> np.uint64(33)
    h *= np.uint64(0xFF51AFD7ED558CCD)
    h ^= h >> np.uint64(33)
    return (h % np.uint64(width)).astype(np.int64)


def _to_u64_keys(col: np.ndarray) -> np.ndarray:
    col = np.asarray(col)
    if col.dtype.kind in "iu":
        return col.astype(np.uint64)
    if col.dtype.kind == "f":
        return col.astype(np.float64).view(np.uint64)
    from geomesa_tpu.utils.hashing import fnv_fold

    return fnv_fold(col)


class Frequency:
    """Count-min sketch for equality selectivity (reference Frequency.scala)."""

    def __init__(self, depth: int = 4, width: int = 1024):
        self.depth = depth
        self.width = width
        self.table = np.zeros((depth, width), dtype=np.int64)
        self.count = 0

    def observe(self, col: np.ndarray) -> None:
        if len(col) == 0:
            return
        self.count += len(col)
        idx = _cm_hashes(_to_u64_keys(col), self.depth, self.width)
        for d in range(self.depth):
            # bincount is ~20x np.add.at; runs per ingest batch
            self.table[d] += np.bincount(idx[d], minlength=self.width)

    def __iadd__(self, other: "Frequency") -> "Frequency":
        self.table += other.table
        self.count += other.count
        return self

    def estimate(self, value) -> int:
        idx = _cm_hashes(_to_u64_keys(np.array([value])), self.depth, self.width)
        return int(min(self.table[d, idx[d, 0]] for d in range(self.depth)))

    def to_json(self):
        return {"depth": self.depth, "width": self.width, "count": int(self.count)}


class TopK:
    """Heavy hitters. Columnar ingest makes exact per-batch counts cheap
    (np.unique); the sketch keeps the top-k across merges (reference
    TopK.scala wraps StreamSummary — same contract, batch-exact here)."""

    def __init__(self, k: int = 10, cap: int = 65536):
        self.k = k
        self.cap = cap
        self.counts: dict = {}

    def observe(self, col: np.ndarray) -> None:
        vals, cnts = np.unique(np.asarray(col), return_counts=True)
        for v, c in zip(vals.tolist(), cnts.tolist()):
            self.counts[v] = self.counts.get(v, 0) + c
        if len(self.counts) > self.cap:
            keep = sorted(self.counts.items(), key=lambda kv: -kv[1])[: self.cap // 2]
            self.counts = dict(keep)

    def __iadd__(self, other: "TopK") -> "TopK":
        for v, c in other.counts.items():
            self.counts[v] = self.counts.get(v, 0) + c
        return self

    def top(self, k: int | None = None) -> list[tuple]:
        return sorted(self.counts.items(), key=lambda kv: -kv[1])[: k or self.k]

    def to_json(self):
        return {"top": [[v, int(c)] for v, c in self.top()]}


class DescriptiveStats:
    """Mergeable moments over one or more numeric attributes: count, min,
    max, sum, mean, population/sample variance + stddev, skewness,
    kurtosis, and pairwise population/sample covariance + correlation
    (reference DescriptiveStats.scala, which wraps commons-math; here the
    moments are held directly and merged with Chan's parallel-update
    formulas, so per-shard sketches combine exactly).
    """

    def __init__(self, n_attrs: int = 1):
        d = n_attrs
        self.d = d
        self.count = 0
        self.min = np.full(d, np.inf)
        self.max = np.full(d, -np.inf)
        self.mean = np.zeros(d)
        self.m2 = np.zeros(d)  # sum of squared deviations (univariate)
        self.m3 = np.zeros(d)
        self.m4 = np.zeros(d)
        self.comoment = np.zeros((d, d))  # sum of deviation products

    def observe(self, *cols) -> None:
        x = np.stack(
            [np.asarray(c, dtype=np.float64) for c in cols], axis=1
        )  # [n, d]
        if x.shape[1] != self.d:
            raise ValueError(f"expected {self.d} columns, got {x.shape[1]}")
        # NaN is the null representation for numeric columns (see
        # filter/predicates IS NULL): a null in any attribute drops the
        # row, keeping the covariance pairing consistent (the reference
        # skips null attributes the same way)
        x = x[~np.isnan(x).any(axis=1)]
        n = len(x)
        if n == 0:
            return
        other = DescriptiveStats.__new__(DescriptiveStats)
        other.d = self.d
        other.count = n
        other.min = x.min(axis=0)
        other.max = x.max(axis=0)
        other.mean = x.mean(axis=0)
        dev = x - other.mean
        other.m2 = (dev**2).sum(axis=0)
        other.m3 = (dev**3).sum(axis=0)
        other.m4 = (dev**4).sum(axis=0)
        other.comoment = dev.T @ dev
        self += other

    def __iadd__(self, other: "DescriptiveStats") -> "DescriptiveStats":
        if other.count == 0:
            return self
        if self.count == 0:
            for f in ("count", "min", "max", "mean", "m2", "m3", "m4", "comoment"):
                setattr(self, f, getattr(other, f))
            return self
        na, nb = self.count, other.count
        n = na + nb
        delta = other.mean - self.mean
        # Chan et al. pairwise central-moment updates
        m2 = self.m2 + other.m2 + delta**2 * na * nb / n
        m3 = (
            self.m3
            + other.m3
            + delta**3 * na * nb * (na - nb) / n**2
            + 3.0 * delta * (na * other.m2 - nb * self.m2) / n
        )
        m4 = (
            self.m4
            + other.m4
            + delta**4 * na * nb * (na**2 - na * nb + nb**2) / n**3
            + 6.0 * delta**2 * (na**2 * other.m2 + nb**2 * self.m2) / n**2
            + 4.0 * delta * (na * other.m3 - nb * self.m3) / n
        )
        self.comoment = (
            self.comoment + other.comoment + np.outer(delta, delta) * na * nb / n
        )
        self.mean = self.mean + delta * nb / n
        self.m2, self.m3, self.m4 = m2, m3, m4
        self.min = np.minimum(self.min, other.min)
        self.max = np.maximum(self.max, other.max)
        self.count = n
        return self

    @property
    def sum(self) -> np.ndarray:
        return self.mean * self.count

    def variance(self, sample: bool = True) -> np.ndarray:
        div = max(self.count - 1, 1) if sample else max(self.count, 1)
        return self.m2 / div

    def stddev(self, sample: bool = True) -> np.ndarray:
        return np.sqrt(self.variance(sample))

    def skewness(self) -> np.ndarray:
        """Population skewness g1 = (M3/n) / (M2/n)^1.5."""
        n = max(self.count, 1)
        s2 = self.m2 / n
        with np.errstate(divide="ignore", invalid="ignore"):
            out = (self.m3 / n) / np.power(s2, 1.5)
        return np.where(s2 > 0, out, 0.0)

    def kurtosis(self) -> np.ndarray:
        """Population excess kurtosis g2 = n*M4/M2^2 - 3."""
        with np.errstate(divide="ignore", invalid="ignore"):
            out = self.count * self.m4 / self.m2**2 - 3.0
        return np.where(self.m2 > 0, out, 0.0)

    def covariance(self, sample: bool = True) -> np.ndarray:
        div = max(self.count - 1, 1) if sample else max(self.count, 1)
        return self.comoment / div

    def correlation(self) -> np.ndarray:
        sd = np.sqrt(np.diag(self.comoment))
        with np.errstate(divide="ignore", invalid="ignore"):
            out = self.comoment / np.outer(sd, sd)
        return np.where(np.outer(sd, sd) > 0, out, 0.0)

    def to_json(self):
        if self.count == 0:
            return {"count": 0}
        return {
            "count": int(self.count),
            "min": self.min.tolist(),
            "max": self.max.tolist(),
            "sum": self.sum.tolist(),
            "mean": self.mean.tolist(),
            "stddev_sample": self.stddev(True).tolist(),
            "variance_sample": self.variance(True).tolist(),
            "stddev_population": self.stddev(False).tolist(),
            "variance_population": self.variance(False).tolist(),
            "skewness": np.asarray(self.skewness()).tolist(),
            "kurtosis": np.asarray(self.kurtosis()).tolist(),
            "covariance_sample": self.covariance(True).tolist(),
            "correlation": self.correlation().tolist(),
        }


class Z3Frequency:
    """Count-min sketch keyed by (time bin, z3 prefix) cells: point-query
    selectivity for spatio-temporal values, complementing Z3Histogram's
    range estimates (reference Z3Frequency.scala)."""

    def __init__(self, total_bits: int, prefix_bits: int = 16,
                 depth: int = 4, width: int = 4096):
        if not 1 <= prefix_bits <= 48:
            raise ValueError(f"prefix_bits must be in [1, 48]: {prefix_bits}")
        self.shift = np.uint64(max(0, total_bits - prefix_bits))
        # retained z bits; bins occupy the field ABOVE them so distinct
        # (bin, prefix) cells can never alias
        self._prefix_bits = np.uint64(min(prefix_bits, total_bits))
        self.freq = Frequency(depth=depth, width=width)

    def _keys(self, bins, zs) -> np.ndarray:
        return (
            np.asarray(bins, dtype=np.uint64) << self._prefix_bits
        ) | (np.asarray(zs, dtype=np.uint64) >> self.shift)

    def observe(self, bins: np.ndarray, zs: np.ndarray) -> None:
        self.freq.observe(self._keys(bins, zs))

    def __iadd__(self, other: "Z3Frequency") -> "Z3Frequency":
        if (self.shift, self._prefix_bits) != (other.shift, other._prefix_bits):
            raise ValueError(
                "cannot merge Z3Frequency sketches with different "
                f"resolutions: {self.to_json()} vs {other.to_json()}"
            )
        self.freq += other.freq
        return self

    @property
    def count(self) -> int:
        return self.freq.count

    def estimate(self, tbin: int, z: int) -> int:
        """Upper-bound count of rows in the cell containing (bin, z)."""
        return self.freq.estimate(self._keys([tbin], [z])[0])

    def to_json(self):
        return {"shift": int(self.shift), **self.freq.to_json()}


class Z3Histogram:
    """Counts over coarse (time bin, z-prefix) cells: the spatio-temporal
    selectivity sketch (reference Z3Histogram.scala). Cells are the top
    ``prefix_bits`` of the z value per time bin; estimates sum matching
    cells for a set of z ranges."""

    def __init__(self, total_bits: int, prefix_bits: int = 16):
        # prefix 16 (round 4; was 12): 12-bit cells were ~6x off on
        # clustered data — too coarse for the kNN local-radius tier. Cells
        # live as parallel SORTED arrays (keys, counts) merged wholesale
        # per batch — a per-cell python dict loop dominated large ingests.
        self.total_bits = total_bits
        self.shift = np.uint64(max(0, total_bits - prefix_bits))
        self._keys = np.zeros(0, dtype=np.int64)
        self._counts = np.zeros(0, dtype=np.int64)

    # rows per observe() pass: larger batches stride-sample down to this
    # (a selectivity sketch needs distribution shape, not exact mass; the
    # full-array unique dominated large ingest batches)
    SAMPLE_CAP = 4_000_000

    @property
    def cells(self) -> dict:
        """(bin, z_prefix) -> count view (tests/inspection)."""
        return dict(zip(self._keys.tolist(), self._counts.tolist()))

    def _merge(self, vals: np.ndarray, cnts: np.ndarray) -> None:
        if len(self._keys) == 0:
            self._keys, self._counts = vals, cnts
            return
        uk, inv = np.unique(
            np.concatenate([self._keys, vals]), return_inverse=True
        )
        uc = np.bincount(
            inv, weights=np.concatenate([self._counts, cnts]), minlength=len(uk)
        ).astype(np.int64)
        self._keys, self._counts = uk, uc

    def observe(self, bins: np.ndarray, zs: np.ndarray) -> None:
        n = len(zs)
        weight = 1
        if n > self.SAMPLE_CAP:
            stride = -(-n // self.SAMPLE_CAP)
            bins = np.ascontiguousarray(bins[::stride])
            zs = np.ascontiguousarray(zs[::stride])
            weight = stride
        key = bins.astype(np.int64) * (1 << 32) + (
            zs.astype(np.uint64) >> self.shift
        ).astype(np.int64)
        vals, cnts = np.unique(key, return_counts=True)
        self._merge(vals, cnts.astype(np.int64) * weight)

    def __iadd__(self, other: "Z3Histogram") -> "Z3Histogram":
        self._merge(other._keys, other._counts)
        return self

    def estimate(self, range_bins, range_lo, range_hi) -> float:
        """Estimated rows covered by inclusive z ranges, assuming uniform
        intra-cell mass."""
        if len(self._keys) == 0:
            return 0.0
        keys, cnts = self._keys, self._counts
        cell = np.uint64(1) << self.shift
        est = 0.0
        for b, lo, hi in zip(
            np.asarray(range_bins).tolist(),
            np.asarray(range_lo, dtype=np.uint64).tolist(),
            np.asarray(range_hi, dtype=np.uint64).tolist(),
        ):
            p_lo = np.uint64(lo) >> self.shift
            p_hi = np.uint64(hi) >> self.shift
            k_lo = b * (1 << 32) + int(p_lo)
            k_hi = b * (1 << 32) + int(p_hi)
            i0 = np.searchsorted(keys, k_lo, side="left")
            i1 = np.searchsorted(keys, k_hi, side="right")
            if i1 <= i0:
                continue
            est += cnts[i0:i1].sum()
            # partial overlap of boundary cells
            frac_lo = float(np.uint64(lo) & (cell - np.uint64(1))) / float(cell)
            frac_hi = 1.0 - float(
                (np.uint64(hi) & (cell - np.uint64(1))) + np.uint64(1)
            ) / float(cell)
            if keys[i0] == k_lo:
                est -= cnts[i0] * frac_lo
            if keys[i1 - 1] == k_hi:
                est -= cnts[i1 - 1] * frac_hi
        return max(est, 0.0)

    def to_json(self):
        return {"cells": len(self._keys), "shift": int(self.shift)}
