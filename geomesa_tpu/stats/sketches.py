"""Stats sketches: MinMax, Histogram, Frequency (count-min), TopK, Z3Histogram.

Reference: the `geomesa-utils` stats package (/root/reference/
geomesa-utils-parent/geomesa-utils/src/main/scala/org/locationtech/geomesa/
utils/stats/ — MinMax.scala, Histogram.scala, Frequency.scala, TopK.scala,
Z3Histogram.scala, parse DSL Stat.scala:30). The reference observes one
feature at a time inside server iterators; the TPU redesign observes whole
columns with vectorized reductions and merges partial sketches with `+=`
(the collective-merge analogue: per-shard sketches psum/concat-merge into
one).
"""

from __future__ import annotations


import numpy as np

__all__ = ["MinMax", "Histogram", "Frequency", "TopK", "Z3Histogram", "CountStat"]


class CountStat:
    """Total observed count (reference CountStat)."""

    def __init__(self):
        self.count = 0

    def observe(self, col: np.ndarray) -> None:
        self.count += len(col)

    def __iadd__(self, other: "CountStat") -> "CountStat":
        self.count += other.count
        return self

    def to_json(self):
        return {"count": int(self.count)}


class MinMax:
    """Min/max bounds of one attribute (reference MinMax.scala)."""

    def __init__(self):
        self.min = None
        self.max = None
        self.count = 0

    def observe(self, col: np.ndarray) -> None:
        col = np.asarray(col)
        if len(col) == 0:
            return
        self.count += len(col)
        lo, hi = col.min(), col.max()
        self.min = lo if self.min is None else min(self.min, lo)
        self.max = hi if self.max is None else max(self.max, hi)

    def __iadd__(self, other: "MinMax") -> "MinMax":
        if other.min is not None:
            self.observe(np.array([other.min, other.max]))
            self.count += other.count - 2
        return self

    @property
    def bounds(self):
        return None if self.min is None else (self.min, self.max)

    def to_json(self):
        if self.min is None:
            return {"min": None, "max": None, "count": 0}
        return {
            "min": self.min.item() if hasattr(self.min, "item") else self.min,
            "max": self.max.item() if hasattr(self.max, "item") else self.max,
            "count": int(self.count),
        }


class Histogram:
    """Fixed-width binned counts over [lo, hi] (reference Histogram.scala:
    the planner's range-selectivity input)."""

    def __init__(self, n_bins: int, lo: float, hi: float):
        if hi <= lo:
            hi = lo + 1.0
        self.n_bins = n_bins
        self.lo = float(lo)
        self.hi = float(hi)
        self.counts = np.zeros(n_bins, dtype=np.int64)

    def observe(self, col: np.ndarray) -> None:
        col = np.asarray(col, dtype=np.float64)
        if len(col) == 0:
            return
        idx = ((col - self.lo) / (self.hi - self.lo) * self.n_bins).astype(np.int64)
        idx = np.clip(idx, 0, self.n_bins - 1)
        # bincount is ~20x np.add.at — this runs per ingest batch
        self.counts += np.bincount(idx, minlength=self.n_bins)

    def __iadd__(self, other: "Histogram") -> "Histogram":
        if (other.lo, other.hi, other.n_bins) == (self.lo, self.hi, self.n_bins):
            self.counts += other.counts
            return self
        # bounds differ across batches: rebin both into the union span
        # (reference Histogram expands via its defined bounds; here bounds
        # are data-derived per batch so the merge rebins proportionally)
        lo, hi = min(self.lo, other.lo), max(self.hi, other.hi)
        n = max(self.n_bins, other.n_bins)
        out = Histogram(n, lo, hi)
        for h in (self, other):
            w = (h.hi - h.lo) / h.n_bins
            centers = h.lo + (np.arange(h.n_bins) + 0.5) * w
            idx = np.clip(
                ((centers - lo) / (hi - lo) * n).astype(np.int64), 0, n - 1
            )
            np.add.at(out.counts, idx, h.counts)
        self.n_bins, self.lo, self.hi, self.counts = n, lo, hi, out.counts
        return self

    def estimate_range(self, lo: float, hi: float) -> float:
        """Estimated count within [lo, hi] assuming uniform intra-bin mass.
        Vectorized: hot-path callers (estimate_bbox, the kNN radius
        refinement) probe this several times per query."""
        w = (self.hi - self.lo) / self.n_bins
        edges = self.lo + np.arange(self.n_bins + 1) * w
        overlap = np.clip(
            np.minimum(hi, edges[1:]) - np.maximum(lo, edges[:-1]), 0.0, w
        )
        return float((self.counts * (overlap / w)).sum())

    def to_json(self):
        return {
            "bins": self.n_bins,
            "lo": self.lo,
            "hi": self.hi,
            "counts": self.counts.tolist(),
        }


def _cm_hashes(keys: np.ndarray, depth: int, width: int) -> np.ndarray:
    """[depth, n] multiply-shift hashes of u64 keys."""
    keys = keys.astype(np.uint64)
    salts = np.array(
        [0x9E3779B97F4A7C15, 0xBF58476D1CE4E5B9, 0x94D049BB133111EB, 0xD6E8FEB86659FD93],
        dtype=np.uint64,
    )[:depth, None]
    h = keys[None, :] * salts
    h ^= h >> np.uint64(33)
    h *= np.uint64(0xFF51AFD7ED558CCD)
    h ^= h >> np.uint64(33)
    return (h % np.uint64(width)).astype(np.int64)


def _to_u64_keys(col: np.ndarray) -> np.ndarray:
    col = np.asarray(col)
    if col.dtype.kind in "iu":
        return col.astype(np.uint64)
    if col.dtype.kind == "f":
        return col.astype(np.float64).view(np.uint64)
    from geomesa_tpu.utils.hashing import fnv_fold

    return fnv_fold(col)


class Frequency:
    """Count-min sketch for equality selectivity (reference Frequency.scala)."""

    def __init__(self, depth: int = 4, width: int = 1024):
        self.depth = depth
        self.width = width
        self.table = np.zeros((depth, width), dtype=np.int64)
        self.count = 0

    def observe(self, col: np.ndarray) -> None:
        if len(col) == 0:
            return
        self.count += len(col)
        idx = _cm_hashes(_to_u64_keys(col), self.depth, self.width)
        for d in range(self.depth):
            # bincount is ~20x np.add.at; runs per ingest batch
            self.table[d] += np.bincount(idx[d], minlength=self.width)

    def __iadd__(self, other: "Frequency") -> "Frequency":
        self.table += other.table
        self.count += other.count
        return self

    def estimate(self, value) -> int:
        idx = _cm_hashes(_to_u64_keys(np.array([value])), self.depth, self.width)
        return int(min(self.table[d, idx[d, 0]] for d in range(self.depth)))

    def to_json(self):
        return {"depth": self.depth, "width": self.width, "count": int(self.count)}


class TopK:
    """Heavy hitters. Columnar ingest makes exact per-batch counts cheap
    (np.unique); the sketch keeps the top-k across merges (reference
    TopK.scala wraps StreamSummary — same contract, batch-exact here)."""

    def __init__(self, k: int = 10, cap: int = 65536):
        self.k = k
        self.cap = cap
        self.counts: dict = {}

    def observe(self, col: np.ndarray) -> None:
        vals, cnts = np.unique(np.asarray(col), return_counts=True)
        for v, c in zip(vals.tolist(), cnts.tolist()):
            self.counts[v] = self.counts.get(v, 0) + c
        if len(self.counts) > self.cap:
            keep = sorted(self.counts.items(), key=lambda kv: -kv[1])[: self.cap // 2]
            self.counts = dict(keep)

    def __iadd__(self, other: "TopK") -> "TopK":
        for v, c in other.counts.items():
            self.counts[v] = self.counts.get(v, 0) + c
        return self

    def top(self, k: int | None = None) -> list[tuple]:
        return sorted(self.counts.items(), key=lambda kv: -kv[1])[: k or self.k]

    def to_json(self):
        return {"top": [[v, int(c)] for v, c in self.top()]}


class Z3Histogram:
    """Counts over coarse (time bin, z-prefix) cells: the spatio-temporal
    selectivity sketch (reference Z3Histogram.scala). Cells are the top
    ``prefix_bits`` of the z value per time bin; estimates sum matching
    cells for a set of z ranges."""

    def __init__(self, total_bits: int, prefix_bits: int = 16):
        # prefix 16 (round 4; was 12): 12-bit cells were ~6x off on
        # clustered data — too coarse for the kNN local-radius tier. Cells
        # live as parallel SORTED arrays (keys, counts) merged wholesale
        # per batch — a per-cell python dict loop dominated large ingests.
        self.total_bits = total_bits
        self.shift = np.uint64(max(0, total_bits - prefix_bits))
        self._keys = np.zeros(0, dtype=np.int64)
        self._counts = np.zeros(0, dtype=np.int64)

    # rows per observe() pass: larger batches stride-sample down to this
    # (a selectivity sketch needs distribution shape, not exact mass; the
    # full-array unique dominated large ingest batches)
    SAMPLE_CAP = 4_000_000

    @property
    def cells(self) -> dict:
        """(bin, z_prefix) -> count view (tests/inspection)."""
        return dict(zip(self._keys.tolist(), self._counts.tolist()))

    def _merge(self, vals: np.ndarray, cnts: np.ndarray) -> None:
        if len(self._keys) == 0:
            self._keys, self._counts = vals, cnts
            return
        uk, inv = np.unique(
            np.concatenate([self._keys, vals]), return_inverse=True
        )
        uc = np.bincount(
            inv, weights=np.concatenate([self._counts, cnts]), minlength=len(uk)
        ).astype(np.int64)
        self._keys, self._counts = uk, uc

    def observe(self, bins: np.ndarray, zs: np.ndarray) -> None:
        n = len(zs)
        weight = 1
        if n > self.SAMPLE_CAP:
            stride = -(-n // self.SAMPLE_CAP)
            bins = np.ascontiguousarray(bins[::stride])
            zs = np.ascontiguousarray(zs[::stride])
            weight = stride
        key = bins.astype(np.int64) * (1 << 32) + (
            zs.astype(np.uint64) >> self.shift
        ).astype(np.int64)
        vals, cnts = np.unique(key, return_counts=True)
        self._merge(vals, cnts.astype(np.int64) * weight)

    def __iadd__(self, other: "Z3Histogram") -> "Z3Histogram":
        self._merge(other._keys, other._counts)
        return self

    def estimate(self, range_bins, range_lo, range_hi) -> float:
        """Estimated rows covered by inclusive z ranges, assuming uniform
        intra-cell mass."""
        if len(self._keys) == 0:
            return 0.0
        keys, cnts = self._keys, self._counts
        cell = np.uint64(1) << self.shift
        est = 0.0
        for b, lo, hi in zip(
            np.asarray(range_bins).tolist(),
            np.asarray(range_lo, dtype=np.uint64).tolist(),
            np.asarray(range_hi, dtype=np.uint64).tolist(),
        ):
            p_lo = np.uint64(lo) >> self.shift
            p_hi = np.uint64(hi) >> self.shift
            k_lo = b * (1 << 32) + int(p_lo)
            k_hi = b * (1 << 32) + int(p_hi)
            i0 = np.searchsorted(keys, k_lo, side="left")
            i1 = np.searchsorted(keys, k_hi, side="right")
            if i1 <= i0:
                continue
            est += cnts[i0:i1].sum()
            # partial overlap of boundary cells
            frac_lo = float(np.uint64(lo) & (cell - np.uint64(1))) / float(cell)
            frac_hi = 1.0 - float(
                (np.uint64(hi) & (cell - np.uint64(1))) + np.uint64(1)
            ) / float(cell)
            if keys[i0] == k_lo:
                est -= cnts[i0] * frac_lo
            if keys[i1 - 1] == k_hi:
                est -= cnts[i1 - 1] * frac_hi
        return max(est, 0.0)

    def to_json(self):
        return {"cells": len(self._keys), "shift": int(self.shift)}
