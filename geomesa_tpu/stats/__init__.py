"""Stats subsystem: ingest-time sketches + the planner cost model's inputs.

Reference: geomesa-index-api stats/ + geomesa-utils stats/ (SURVEY.md §2.2,
§2.5).
"""

from geomesa_tpu.stats.sketches import (
    CountStat,
    DescriptiveStats,
    Frequency,
    Histogram,
    MinMax,
    TopK,
    Z3Frequency,
    Z3Histogram,
)
from geomesa_tpu.stats.store import StatsStore

__all__ = [
    "CountStat",
    "DescriptiveStats",
    "Frequency",
    "Histogram",
    "MinMax",
    "TopK",
    "Z3Frequency",
    "Z3Histogram",
    "StatsStore",
]
