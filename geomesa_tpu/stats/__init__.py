"""Stats subsystem: ingest-time sketches + the planner cost model's inputs.

Reference: geomesa-index-api stats/ + geomesa-utils stats/ (SURVEY.md §2.2,
§2.5).
"""

from geomesa_tpu.stats.sketches import (
    CountStat,
    Frequency,
    Histogram,
    MinMax,
    TopK,
    Z3Histogram,
)
from geomesa_tpu.stats.store import StatsStore

__all__ = [
    "CountStat",
    "Frequency",
    "Histogram",
    "MinMax",
    "TopK",
    "Z3Histogram",
    "StatsStore",
]
