"""Stat DSL: parse "Count();MinMax(attr);..." and evaluate over a batch.

Reference: the parseable Stat grammar (/root/reference/
geomesa-utils-parent/geomesa-utils/src/main/scala/org/locationtech/geomesa/
utils/stats/Stat.scala:30-120) driving server-side StatsScan aggregation
(geomesa-index-api/.../iterators/StatsScan.scala). Supported here:

    Count()
    MinMax(attr)
    Enumeration(attr)            -> exact value counts (TopK with k=all)
    TopK(attr[,k])
    Frequency(attr[,width])      -> count-min sketch
    Histogram(attr,bins,lo,hi)
    DescriptiveStats(attr[,attr2,...]) -> moments + covariance/correlation
    GroupBy(attr,<stat>)         -> one sub-stat per distinct value

A ';'-separated list IS the reference's SeqStat: parse() returns one
sketch per term and merge is element-wise.

Stats evaluate column-at-a-time over a FeatureCollection (the reference
folds one feature at a time inside iterators) and merge with ``+=`` for
the sharded path.
"""

from __future__ import annotations

import re

import numpy as np

from geomesa_tpu.stats.sketches import (
    CountStat,
    DescriptiveStats,
    Frequency,
    Histogram,
    MinMax,
    TopK,
)

_CALL = re.compile(r"^\s*(\w+)\((.*)\)\s*$", re.S)


def _split_args(s: str) -> list[str]:
    """Split top-level comma args (GroupBy nests parenthesized calls)."""
    args, depth, cur = [], 0, []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            args.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    tail = "".join(cur).strip()
    if tail:
        args.append(tail)
    return args


def _strip(a: str) -> str:
    return a.strip().strip("'\"")


class _Eval:
    """One parsed stat term bound to an attribute."""

    def __init__(self, kind: str, attr: str | None, make, sub=None):
        self.kind = kind
        self.attr = attr
        self.make = make
        self.sub = sub  # GroupBy inner spec string

    def observe(self, fc) -> object:
        sk = self.make()
        if self.kind == "count":
            sk.observe(np.zeros(len(fc)))
            return sk
        if self.kind == "descriptive":  # attr is a LIST of attributes
            sk.observe(*[_column(fc, a) for a in self.attr])
            return sk
        col = _column(fc, self.attr)
        if self.kind == "groupby":
            groups = {}
            vals = np.asarray(col)
            for v in np.unique(vals):
                groups[v.item() if hasattr(v, "item") else v] = evaluate(
                    self.sub, fc.mask(vals == v)
                )
            return groups
        sk.observe(col)
        return sk


def _column(fc, attr: str) -> np.ndarray:
    col = fc.columns[attr]
    if hasattr(col, "x"):  # PointColumn: observe lon for MinMax-style stats
        return col.x
    return np.asarray(col)


def parse_one(spec: str) -> _Eval:
    m = _CALL.match(spec)
    if not m:
        raise ValueError(f"cannot parse stat {spec!r}")
    name, raw = m.group(1).lower(), m.group(2)
    args = _split_args(raw)
    if name == "count":
        return _Eval("count", None, CountStat)
    if name == "minmax":
        return _Eval("minmax", _strip(args[0]), MinMax)
    if name in ("enumeration", "enum"):
        # exact counts: disable both the top-k trim and the cap eviction
        return _Eval("topk", _strip(args[0]), lambda: TopK(k=1 << 30, cap=1 << 30))
    if name == "topk":
        k = int(args[1]) if len(args) > 1 else 10
        return _Eval("topk", _strip(args[0]), lambda: TopK(k=k))
    if name == "frequency":
        width = int(args[1]) if len(args) > 1 else 1024
        return _Eval("frequency", _strip(args[0]), lambda: Frequency(width=width))
    if name == "histogram":
        bins, lo, hi = int(args[1]), float(args[2]), float(args[3])
        return _Eval("histogram", _strip(args[0]), lambda: Histogram(bins, lo, hi))
    if name in ("descriptivestats", "descriptive", "stats"):
        attrs = [_strip(a) for a in args]
        if not attrs:
            raise ValueError("DescriptiveStats requires at least one attribute")
        return _Eval(
            "descriptive", attrs, lambda: DescriptiveStats(len(attrs))
        )
    if name == "groupby":
        # sub-stats re-enter the term grammar, which is ';'-separated
        return _Eval("groupby", _strip(args[0]), dict, sub=";".join(args[1:]))
    raise ValueError(f"unknown stat {name!r}")


def parse(spec: str) -> list[_Eval]:
    return [parse_one(s) for s in spec.split(";") if s.strip()]


def evaluate_terms(terms: list, fc) -> list:
    return [term.observe(fc) for term in terms]


def evaluate(spec: str, fc) -> list:
    """Evaluate a stat spec string over a FeatureCollection; returns one
    sketch (or GroupBy dict) per ';'-separated term."""
    return evaluate_terms(parse(spec), fc)


def to_json(results: list) -> list:
    def conv(r):
        if isinstance(r, dict):
            return {str(k): to_json(v) for k, v in r.items()}
        return r.to_json()

    return [conv(r) for r in results]
