"""StatsStore: per-schema sketches maintained at ingest, serving the
planner's cost model and user-facing stats queries.

Reference: GeoMesaStats (/root/reference/geomesa-index-api/src/main/scala/
org/locationtech/geomesa/index/stats/GeoMesaStats.scala:30-110) — counts,
bounds, min/max, histograms — persisted as sketches by MetadataBackedStats
and consumed by CostBasedStrategyDecider. Here the sketches are built with
one pass of vectorized column reductions per write batch.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.stats.sketches import (
    CountStat,
    Frequency,
    Histogram,
    MinMax,
    TopK,
    Z3Histogram,
)

HISTOGRAM_BINS = 1000


class StatsStore:
    """Sketch bundle for one feature type."""

    def __init__(self, sft):
        self.sft = sft
        self.count = CountStat()
        self.minmax: dict[str, MinMax] = {}
        self.histograms: dict[str, Histogram] = {}
        self.frequencies: dict[str, Frequency] = {}
        self.topk: dict[str, TopK] = {}
        self.z3: Z3Histogram | None = None

    # -- build -----------------------------------------------------------
    @staticmethod
    def build(sft, fc) -> "StatsStore":
        from geomesa_tpu.filter.predicates import PointColumn

        st = StatsStore(sft)
        st.count.observe(fc.ids)
        for attr in sft.attributes:
            col = fc.columns.get(attr.name)
            if col is None:
                continue
            if attr.is_geometry:
                if isinstance(col, PointColumn):
                    xs, ys = col.x, col.y
                else:
                    b = col.bboxes  # [n, 4] xmin ymin xmax ymax
                    xs = np.concatenate([b[:, 0], b[:, 2]])
                    ys = np.concatenate([b[:, 1], b[:, 3]])
                mm_x, mm_y = MinMax(), MinMax()
                mm_x.observe(xs)
                mm_y.observe(ys)
                st.minmax[attr.name + ".x"] = mm_x
                st.minmax[attr.name + ".y"] = mm_y
                continue
            col = np.asarray(col)
            if col.dtype.kind in "iuf" or attr.type == "Date":
                mm = MinMax()
                mm.observe(col)
                st.minmax[attr.name] = mm
                if mm.bounds is not None:
                    h = Histogram(
                        HISTOGRAM_BINS, float(mm.min), float(mm.max) + 1e-9
                    )
                    h.observe(col.astype(np.float64))
                    st.histograms[attr.name] = h
            else:
                f = Frequency()
                f.observe(col)
                st.frequencies[attr.name] = f
                tk = TopK()
                tk.observe(col)
                st.topk[attr.name] = tk
        return st

    def observe_index_keys(self, index_name: str, bins, zs, total_bits: int) -> None:
        """Feed (bin, z) write keys into the spatio-temporal sketch."""
        if index_name in ("z3", "z2"):
            if self.z3 is None:
                self.z3 = Z3Histogram(total_bits)
            self.z3.observe(np.asarray(bins), np.asarray(zs))

    def merge(self, other: "StatsStore") -> "StatsStore":
        """Partial-sketch merge (per-shard stats -> one; the collective
        reduce analogue)."""
        self.count += other.count
        for d_name in ("minmax", "histograms", "frequencies", "topk"):
            mine, theirs = getattr(self, d_name), getattr(other, d_name)
            for k, v in theirs.items():
                if k in mine:
                    mine[k] += v
                else:
                    mine[k] = v
        if other.z3 is not None:
            if self.z3 is None:
                self.z3 = other.z3
            else:
                self.z3 += other.z3
        return self

    # -- planner queries -------------------------------------------------
    def total_count(self) -> int:
        return self.count.count

    def estimate_scan(self, index_name: str, cfg) -> float | None:
        """Estimated rows a scan config touches (cost-model input)."""
        if self.z3 is not None and index_name in ("z3", "z2"):
            return self.z3.estimate(cfg.range_bins, cfg.range_lo, cfg.range_hi)
        return None

    def estimate_equality(self, attr: str, value) -> float | None:
        f = self.frequencies.get(attr)
        return float(f.estimate(value)) if f is not None else None

    def estimate_range(self, attr: str, lo: float, hi: float) -> float | None:
        h = self.histograms.get(attr)
        return h.estimate_range(lo, hi) if h is not None else None

    def attribute_bounds(self, attr: str):
        mm = self.minmax.get(attr)
        return mm.bounds if mm is not None else None

    def to_json(self) -> dict:
        return {
            "count": self.count.to_json(),
            "minmax": {k: v.to_json() for k, v in self.minmax.items()},
            "topk": {k: v.to_json() for k, v in self.topk.items()},
        }
