"""StatsStore: per-schema sketches maintained at ingest, serving the
planner's cost model and user-facing stats queries.

Reference: GeoMesaStats (/root/reference/geomesa-index-api/src/main/scala/
org/locationtech/geomesa/index/stats/GeoMesaStats.scala:30-110) — counts,
bounds, min/max, histograms — persisted as sketches by MetadataBackedStats
and consumed by CostBasedStrategyDecider. Here the sketches are built with
one pass of vectorized column reductions per write batch.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.stats.sketches import (
    CountStat,
    Frequency,
    Histogram,
    MinMax,
    TopK,
    Z3Histogram,
)

HISTOGRAM_BINS = 1000


class StatsStore:
    """Sketch bundle for one feature type."""

    def __init__(self, sft):
        self.sft = sft
        self.count = CountStat()
        self.minmax: dict[str, MinMax] = {}
        self.histograms: dict[str, Histogram] = {}
        self.frequencies: dict[str, Frequency] = {}
        self.topk: dict[str, TopK] = {}
        self.z3: Z3Histogram | None = None
        # which index's keys feed the z sketch ("z3" or "z2"): estimates
        # are only valid for ranges in THAT index's key space — z2 ranges
        # against a z3-keyed sketch silently estimate ~0
        self.z_index: "str | None" = None

    # -- build -----------------------------------------------------------
    @staticmethod
    def build(sft, fc) -> "StatsStore":
        from geomesa_tpu.filter.predicates import PointColumn

        st = StatsStore(sft)
        st.count.observe(fc.ids)
        for attr in sft.attributes:
            col = fc.columns.get(attr.name)
            if col is None:
                continue
            if attr.is_geometry:
                if isinstance(col, PointColumn):
                    xs, ys = col.x, col.y
                else:
                    b = col.bboxes  # [n, 4] xmin ymin xmax ymax
                    xs = np.concatenate([b[:, 0], b[:, 2]])
                    ys = np.concatenate([b[:, 1], b[:, 3]])
                mm_x, mm_y = MinMax(), MinMax()
                mm_x.observe(xs)
                mm_y.observe(ys)
                st.minmax[attr.name + ".x"] = mm_x
                st.minmax[attr.name + ".y"] = mm_y
                # marginal coordinate histograms: the bbox selectivity
                # estimator (independence product) — much finer spatial
                # resolution than the z-prefix sketch for bbox-only
                # probes on z3-keyed stores
                for suffix, vals, mm in ((".x", xs, mm_x), (".y", ys, mm_y)):
                    if mm.bounds is not None:
                        h = Histogram(
                            HISTOGRAM_BINS, float(mm.min), float(mm.max) + 1e-9
                        )
                        h.observe(np.asarray(vals, dtype=np.float64))
                        st.histograms[attr.name + suffix] = h
                continue
            if attr.type == "Bytes":
                # opaque blobs: equality/range selectivity sketches are
                # meaningless and str-hashing binary data crashes
                continue
            col = np.asarray(col)
            if col.dtype.kind in "iuf" or attr.type == "Date":
                mm = MinMax()
                mm.observe(col)
                st.minmax[attr.name] = mm
                if mm.bounds is not None:
                    h = Histogram(
                        HISTOGRAM_BINS, float(mm.min), float(mm.max) + 1e-9
                    )
                    h.observe(col.astype(np.float64))
                    st.histograms[attr.name] = h
            else:
                if col.dtype.kind == "O":
                    # nulls sketch as "" (IsNull's empty-string semantics);
                    # np.unique cannot sort mixed None/str
                    col = np.array(["" if v is None else str(v) for v in col])
                f = Frequency()
                f.observe(col)
                st.frequencies[attr.name] = f
                tk = TopK()
                tk.observe(col)
                st.topk[attr.name] = tk
        return st

    def observe_index_keys(self, index_name: str, bins, zs, total_bits: int) -> None:
        """Feed (bin, z) write keys into the spatio-temporal sketch."""
        if index_name in ("z3", "z2"):
            if self.z3 is None:
                self.z3 = Z3Histogram(total_bits)
                self.z_index = index_name
            self.z3.observe(np.asarray(bins), np.asarray(zs))

    def merge(self, other: "StatsStore") -> "StatsStore":
        """Partial-sketch merge (per-shard stats -> one; the collective
        reduce analogue)."""
        self.count += other.count
        for d_name in ("minmax", "histograms", "frequencies", "topk"):
            mine, theirs = getattr(self, d_name), getattr(other, d_name)
            for k, v in theirs.items():
                if k in mine:
                    mine[k] += v
                else:
                    mine[k] = v
        if other.z3 is not None:
            if self.z3 is None:
                self.z3 = other.z3
                self.z_index = other.z_index
            else:
                self.z3 += other.z3
        return self

    # -- planner queries -------------------------------------------------
    def total_count(self) -> int:
        return self.count.count

    def estimate_scan(self, index_name: str, cfg) -> float | None:
        """Estimated rows a scan config touches (cost-model input)."""
        if self.z3 is not None and index_name == self.z_index:
            return self.z3.estimate(cfg.range_bins, cfg.range_lo, cfg.range_hi)
        return None

    def estimate_equality(self, attr: str, value) -> float | None:
        f = self.frequencies.get(attr)
        return float(f.estimate(value)) if f is not None else None

    def estimate_range(self, attr: str, lo: float, hi: float) -> float | None:
        h = self.histograms.get(attr)
        return h.estimate_range(lo, hi) if h is not None else None

    def estimate_bbox(self, geom: str, x0, y0, x1, y1) -> float | None:
        """Estimated rows intersecting a bbox from the marginal coordinate
        histograms under independence (reference StatsBasedEstimator's
        attribute-selectivity composition). Correlated multi-cluster data
        can overestimate; callers treat this as a selectivity hint."""
        hx = self.histograms.get(geom + ".x")
        hy = self.histograms.get(geom + ".y")
        n = self.total_count()
        if hx is None or hy is None or not n:
            return None
        tx = float(hx.counts.sum())
        ty = float(hy.counts.sum())
        if tx <= 0 or ty <= 0:
            return None
        fx = hx.estimate_range(float(x0), float(x1)) / tx
        fy = hy.estimate_range(float(y0), float(y1)) / ty
        return n * fx * fy

    def estimate_filter(self, sft, f) -> float | None:
        """Selectivity-product estimate for a filter's spatial and temporal
        parts: bbox marginals x date-histogram fraction. None when neither
        axis is constrained or sketches are missing."""
        from geomesa_tpu.filter.extract import (
            extract_geometries, extract_intervals, geometry_bounds,
        )

        n = self.total_count()
        if not n or sft.geom_field is None:
            return None
        geoms = extract_geometries(f, sft.geom_field)
        if geoms.disjoint:
            return 0.0
        est = None
        if geoms.values:
            parts = [
                self.estimate_bbox(sft.geom_field, *b)
                for b in geometry_bounds(geoms)
            ]
            if any(p is None for p in parts):
                return None
            est = min(float(np.sum(parts)), float(n))
        if sft.dtg_field is not None:
            intervals = extract_intervals(f, sft.dtg_field)
            if intervals.disjoint:
                return 0.0
            if intervals.values:
                h = self.histograms.get(sft.dtg_field)
                if h is not None and h.counts.sum() > 0:
                    frac = sum(
                        h.estimate_range(float(iv.lo), float(iv.hi))
                        for iv in intervals.values
                    ) / float(h.counts.sum())
                    frac = min(frac, 1.0)
                    est = n * frac if est is None else est * frac
        return est

    def attribute_bounds(self, attr: str):
        mm = self.minmax.get(attr)
        return mm.bounds if mm is not None else None

    def to_json(self) -> dict:
        return {
            "count": self.count.to_json(),
            "minmax": {k: v.to_json() for k, v in self.minmax.items()},
            "topk": {k: v.to_json() for k, v in self.topk.items()},
        }
