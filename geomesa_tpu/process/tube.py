"""Tube select: spatio-temporal corridor search along a track.

Reference: TubeSelectProcess + TubeBuilder (/root/reference/
geomesa-process/src/main/scala/org/locationtech/geomesa/process/tube/
TubeSelectProcess.scala:36, TubeBuilder.scala) — bins an input track into
time slices, buffers each slice's geometry, and queries features that fall
inside the moving buffer both spatially and temporally. The TPU redesign
bins the track the same way (``bin_ms`` slices, interpolating positions),
issues one Or-of-(bbox And interval) indexed query, and refines with a
vectorized distance test against each row's own time-matched tube center.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.filter.predicates import And, BBox, During, Filter, Include, Or
from geomesa_tpu.process.knn import _meters_to_degrees, haversine_m


def tube_select(
    store,
    type_name: str,
    track_xy: "np.ndarray | list",
    track_times_ms: "np.ndarray | list",
    buffer_m: float,
    bin_ms: int | None = None,
    filter: Filter = Include(),
    max_bins: int = 256,
) -> FeatureCollection:
    """Features within ``buffer_m`` of the track position at their own time.

    ``track_xy``: [n, 2] lon/lat waypoints; ``track_times_ms``: [n] epoch
    millis, ascending. ``bin_ms`` defaults to the track duration / number
    of waypoints (the reference's default binning).
    """
    xy = np.asarray(track_xy, dtype=np.float64).reshape(-1, 2)
    ts = np.asarray(track_times_ms, dtype=np.int64)
    if len(xy) != len(ts) or len(xy) < 2:
        raise ValueError("track needs >= 2 (point, time) pairs")
    if not (np.diff(ts) >= 0).all():
        raise ValueError("track times must be ascending")
    sft = store.get_schema(type_name)
    if sft.dtg_field is None:
        raise ValueError("tube select requires a time attribute")
    geom, dtg = sft.geom_field, sft.dtg_field

    span = int(ts[-1] - ts[0])
    if bin_ms is None:
        bin_ms = max(1, span // max(1, len(xy)))
    n_bins = min(max_bins, max(1, -(-span // bin_ms)))
    bin_ms = -(-span // n_bins)

    # interpolated tube center per bin midpoint
    mids = ts[0] + bin_ms * np.arange(n_bins) + bin_ms // 2
    cx = np.interp(mids, ts, xy[:, 0])
    cy = np.interp(mids, ts, xy[:, 1])

    parts = []
    for i in range(n_bins):
        lo = int(ts[0] + i * bin_ms)
        hi = int(min(ts[0] + (i + 1) * bin_ms, ts[-1] + 1))
        deg = _meters_to_degrees(buffer_m, cy[i])
        # widen by the intra-bin track movement so interpolation error
        # cannot exclude a true hit
        j0, j1 = np.searchsorted(ts, [lo, hi])
        seg_x = np.concatenate([[cx[i]], xy[max(0, j0 - 1) : j1 + 1, 0]])
        seg_y = np.concatenate([[cy[i]], xy[max(0, j0 - 1) : j1 + 1, 1]])
        parts.append(
            And(
                (
                    BBox(
                        geom,
                        float(seg_x.min()) - deg,
                        max(float(seg_y.min()) - deg, -90.0),
                        float(seg_x.max()) + deg,
                        min(float(seg_y.max()) + deg, 90.0),
                    ),
                    During(dtg, lo, hi),
                )
            )
        )
    tube: Filter = parts[0] if len(parts) == 1 else Or(tuple(parts))
    f = tube if isinstance(filter, Include) else And((tube, filter))
    out = store.query(type_name, f)
    if len(out) == 0:
        return out

    # refine: distance from each hit to the track position at the hit's time
    hx, hy = out.representative_xy()
    ht = np.asarray(out.columns[dtg], dtype=np.int64)
    px = np.interp(ht, ts, xy[:, 0])
    py = np.interp(ht, ts, xy[:, 1])
    d = haversine_m(hx, hy, px, py)
    return out.mask(d <= buffer_m)


def standing_tube(
    lam,
    sub_id: str,
    track_xy: "np.ndarray | list",
    track_times_ms: "np.ndarray | list",
    buffer_m: float,
    attrs: "dict | None" = None,
):
    """:func:`tube_select`, STANDING (docs/standing.md): register the
    corridor as a persistent subscription on a
    :class:`~geomesa_tpu.streaming.LambdaStore` — every arriving batch
    routes through the inverted SubscriptionIndex and events within
    ``buffer_m`` of the interpolated track position AT THE EVENT'S OWN
    TIME deliver alerts (events without a usable time never match, the
    TubeSelectProcess refinement). Returns the registered
    :class:`~geomesa_tpu.streaming.Subscription`."""
    from geomesa_tpu.streaming.standing import Subscription

    xy = np.asarray(track_xy, np.float64).reshape(-1, 2)
    ts = np.asarray(track_times_ms, np.int64)
    if len(xy) != len(ts) or len(xy) < 2:
        raise ValueError("track needs >= 2 (point, time) pairs")
    if not (np.diff(ts) >= 0).all():
        raise ValueError("track times must be ascending")
    sub = Subscription(
        str(sub_id), "tube", track_xy=xy, track_times_ms=ts,
        buffer_m=float(buffer_m), attrs=dict(attrs or {}),
    )
    lam.subscribe(sub)
    return sub
