"""Join process: correlate two feature types by attribute value.

Reference: JoinProcess (/root/reference/geomesa-process/src/main/scala/
org/locationtech/geomesa/process/query/JoinProcess.scala) — queries a
primary type, collects the join-attribute values of the hits, and returns
the features of a secondary type whose join attribute matches (each
distinct value queried through the secondary store's attribute index when
present). The columnar inversion: one vectorized membership test via
np.isin over the secondary candidates instead of per-value queries."""

from __future__ import annotations

import numpy as np

from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.filter.predicates import And, Filter, In, Include


def join_search(
    store,
    primary_type: str,
    secondary_type: str,
    join_attribute: str,
    primary_filter: "Filter | str" = Include(),
    secondary_filter: "Filter | str | None" = None,
    max_values: int = 10_000,
) -> FeatureCollection:
    """Features of ``secondary_type`` whose ``join_attribute`` value occurs
    among the ``primary_filter`` hits of ``primary_type``.

    ``max_values`` caps the number of distinct join values pushed into the
    secondary query's IN predicate (the planner routes it through the
    attribute index when one exists); past the cap the secondary side runs
    ``secondary_filter`` alone and membership applies as one vectorized
    host mask.
    """
    kinds = []
    for t, name in ((primary_type, "primary"), (secondary_type, "secondary")):
        sft = store.get_schema(t)
        attr = next((a for a in sft.attributes if a.name == join_attribute), None)
        if attr is None:
            raise ValueError(
                f"{name} type {t!r} has no attribute {join_attribute!r}"
            )
        if attr.is_geometry:
            raise ValueError(
                f"cannot join on geometry attribute {join_attribute!r}; "
                "use the spatial join (geomesa_tpu.sql.join)"
            )
        kinds.append(attr.type)
    if kinds[0] != kinds[1]:
        raise ValueError(
            f"join attribute {join_attribute!r} has mismatched types: "
            f"{kinds[0]} (primary) vs {kinds[1]} (secondary)"
        )
    hits = store.query(primary_type, primary_filter)
    if len(hits) == 0:
        # empty result in the SECONDARY type's shape
        return FeatureCollection.from_rows(store.get_schema(secondary_type), [])
    values = np.unique(np.asarray(hits.columns[join_attribute]))

    if len(values) <= max_values:
        pred: Filter = In(join_attribute, tuple(values.tolist()))
        if secondary_filter is not None and not isinstance(secondary_filter, Include):
            from geomesa_tpu.filter import ecql

            sec = (
                ecql.parse(secondary_filter)
                if isinstance(secondary_filter, str)
                else secondary_filter
            )
            pred = And((pred, sec))
        return store.query(secondary_type, pred)

    out = store.query(secondary_type, secondary_filter or Include())
    mask = np.isin(np.asarray(out.columns[join_attribute]), values)
    return out.mask(mask)
