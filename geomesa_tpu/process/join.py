"""Join process: correlate two feature types by attribute value.

Reference: JoinProcess (/root/reference/geomesa-process/src/main/scala/
org/locationtech/geomesa/process/query/JoinProcess.scala) — queries a
primary type, collects the join-attribute values of the hits, and returns
the features of a secondary type whose join attribute matches (each
distinct value queried through the secondary store's attribute index when
present). The columnar inversion: one vectorized membership test via
np.isin over the secondary candidates instead of per-value queries.

Strategy selection (round 7) is measured, not assumed: past the
``max_values`` IN-push-down cap the fallback to a host membership mask is
COUNTED (``geomesa.join.in_cap_fallback``) and surfaced in the explain
trace instead of happening invisibly, and below the cap a sampled
secondary-side selectivity check (arXiv 1802.09488) skips the push-down
when most secondary rows would match anyway — the IN scan would return
nearly the whole table just to intersect it with itself."""

from __future__ import annotations

import numpy as np

from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.filter.predicates import And, Filter, In, Include
from geomesa_tpu.metrics import resolve as _resolve_metrics
from geomesa_tpu.planning.explain import ExplainNull

# secondary rows sampled for the selectivity estimate (vectorized isin
# over a slice — cheap next to either join strategy)
_SELECTIVITY_SAMPLE = 8192


def join_search(
    store,
    primary_type: str,
    secondary_type: str,
    join_attribute: str,
    primary_filter: "Filter | str" = Include(),
    secondary_filter: "Filter | str | None" = None,
    max_values: int = 10_000,
    explain=None,
    metrics=None,
) -> FeatureCollection:
    """Features of ``secondary_type`` whose ``join_attribute`` value occurs
    among the ``primary_filter`` hits of ``primary_type``.

    ``max_values`` caps the number of distinct join values pushed into the
    secondary query's IN predicate (the planner routes it through the
    attribute index when one exists). The host membership mask replaces
    the push-down when (a) the cap is exceeded — counted by
    ``geomesa.join.in_cap_fallback`` — or (b) the sampled fraction of
    matching secondary rows exceeds ``geomesa.join.in.selectivity``
    (the scan would return most rows anyway). ``explain``: optional
    Explainer tracing the chosen strategy; ``metrics``: optional
    MetricsRegistry (the process-global registry by default).
    """
    exp = explain or ExplainNull()
    metrics = _resolve_metrics(metrics)
    kinds = []
    for t, name in ((primary_type, "primary"), (secondary_type, "secondary")):
        sft = store.get_schema(t)
        attr = next((a for a in sft.attributes if a.name == join_attribute), None)
        if attr is None:
            raise ValueError(
                f"{name} type {t!r} has no attribute {join_attribute!r}"
            )
        if attr.is_geometry:
            raise ValueError(
                f"cannot join on geometry attribute {join_attribute!r}; "
                "use the spatial join (geomesa_tpu.sql.join)"
            )
        kinds.append(attr.type)
    if kinds[0] != kinds[1]:
        raise ValueError(
            f"join attribute {join_attribute!r} has mismatched types: "
            f"{kinds[0]} (primary) vs {kinds[1]} (secondary)"
        )
    hits = store.query(primary_type, primary_filter)
    if len(hits) == 0:
        # empty result in the SECONDARY type's shape
        return FeatureCollection.from_rows(store.get_schema(secondary_type), [])
    values = np.unique(np.asarray(hits.columns[join_attribute]))

    if len(values) > max_values:
        # the silent past-cap fallback, made visible: counted and traced
        metrics.counter("geomesa.join.in_cap_fallback")
        exp(
            f"Join strategy: host membership mask ({len(values)} distinct "
            f"values > max_values {max_values}; "
            "geomesa.join.in_cap_fallback)"
        )
        return _host_mask(store, secondary_type, secondary_filter,
                          join_attribute, values)

    # measured-selectivity gate: sample the secondary column; if most
    # rows match, the IN push-down scans ~everything for nothing. Only
    # consulted when the value set is big enough for low selectivity to
    # be plausible — tiny value sets are inherently selective, and the
    # probe itself materializes the secondary collection (features()
    # concatenates every chunk), a cost the push-down path must not pay
    # just to confirm it was right.
    if len(values) > max(64, max_values // 8):
        from geomesa_tpu.conf import JOIN_IN_SELECTIVITY

        sec = store.features(secondary_type)
        if len(sec):
            col = np.asarray(sec.columns[join_attribute])
            step = max(len(col) // _SELECTIVITY_SAMPLE, 1)
            frac = float(np.isin(col[::step], values).mean())
            if frac >= float(JOIN_IN_SELECTIVITY.get()):
                metrics.counter("geomesa.join.in_skipped_selectivity")
                exp(
                    f"Join strategy: host membership mask (sampled "
                    f"secondary selectivity {frac:.2f} >= "
                    "geomesa.join.in.selectivity)"
                )
                return _host_mask(store, secondary_type, secondary_filter,
                                  join_attribute, values)

    metrics.counter("geomesa.join.in_pushdown")
    exp(f"Join strategy: IN push-down ({len(values)} distinct values)")
    pred: Filter = In(join_attribute, tuple(values.tolist()))
    if secondary_filter is not None and not isinstance(secondary_filter, Include):
        from geomesa_tpu.filter import ecql

        sec_f = (
            ecql.parse(secondary_filter)
            if isinstance(secondary_filter, str)
            else secondary_filter
        )
        pred = And((pred, sec_f))
    return store.query(secondary_type, pred)


def _host_mask(store, secondary_type, secondary_filter, join_attribute, values):
    """The membership-mask strategy: run the secondary filter alone and
    apply the join values as one vectorized isin mask."""
    out = store.query(secondary_type, secondary_filter or Include())
    mask = np.isin(np.asarray(out.columns[join_attribute]), values)
    return out.mask(mask)
