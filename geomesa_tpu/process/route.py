"""Route search: features along a route, heading-matched to its direction.

Reference: RouteSearchProcess (/root/reference/geomesa-process/
geomesa-process-vector/src/main/scala/org/locationtech/geomesa/process/
query/RouteSearchProcess.scala:40-260) — buffers the route linestrings
(dwithin, meters), then keeps candidates whose heading matches the
heading of the *closest route segment* within a threshold (optionally
bidirectional, i.e. either direction along the path).

TPU redesign: the per-feature JTS DistanceOp + GeodeticCalculator loop
becomes one store query over the buffered route envelopes followed by a
vectorized candidate x segment distance/bearing computation (chunked to
bound memory). Distances/bearings use a local equirectangular projection
per candidate (exact enough at buffer scale; the reference's geodetic
calculator differs sub-degree over typical buffers).
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu import geometry as geo
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.filter.predicates import And, Filter, Include, Or
from geomesa_tpu.process.knn import (
    METERS_PER_DEGREE, _meters_to_degrees, wrap_box_filter,
)

_CHUNK = 4_000_000  # max candidate x segment pairs per vectorized block
_MAX_ENVELOPES = 128  # cap on buffered query boxes (segments chunk up)


def _route_coords(route) -> np.ndarray:
    """A route input (LineString, [m, 2] array, or WKT string) -> [m, 2]."""
    if isinstance(route, geo.LineString):
        return np.asarray(route.coords, dtype=np.float64)
    if isinstance(route, str):
        g = geo.from_wkt(route)
        if not isinstance(g, geo.LineString):
            raise ValueError("route WKT must be a LINESTRING")
        return np.asarray(g.coords, dtype=np.float64)
    a = np.asarray(route, dtype=np.float64)
    if a.ndim != 2 or a.shape[1] != 2 or len(a) < 2:
        raise ValueError("route must be an [m>=2, 2] coordinate array")
    return a


def _segment_bearings(a: np.ndarray, b: np.ndarray, lat_ref: np.ndarray) -> np.ndarray:
    """Compass bearings (degrees clockwise from north, [0, 360)) of
    segments a->b under the local equirectangular projection."""
    dx = (b[:, 0] - a[:, 0]) * np.cos(np.radians(lat_ref))
    dy = b[:, 1] - a[:, 1]
    return (np.degrees(np.arctan2(dx, dy)) + 360.0) % 360.0


def heading_diff(a, b) -> np.ndarray:
    """Absolute compass-heading difference in [0, 180]."""
    d = np.abs(np.asarray(a, dtype=np.float64) - np.asarray(b, dtype=np.float64))
    return np.where(d > 180.0, np.abs(d - 360.0), d)


def route_search(
    store,
    type_name: str,
    routes,
    buffer_m: float,
    heading_threshold_deg: float,
    heading_field: "str | None" = None,
    bidirectional: bool = False,
    filter: Filter = Include(),
) -> FeatureCollection:
    """Features within ``buffer_m`` meters of any route whose heading
    matches the closest route segment within ``heading_threshold_deg``.

    ``heading_field``: attribute holding each feature's compass heading.
    Required for point features (reference behavior); linestring features
    default to the bearing of their last segment, compared at their end
    point (the track's "current" position/heading).
    """
    segs_a, segs_b = _route_segments(routes)
    if len(segs_a) == 0:
        return store.features(type_name).take(np.zeros(0, dtype=np.int64))

    sft = store.get_schema(type_name)
    geom = sft.geom_field
    if heading_field is None and sft.is_points:
        raise ValueError(
            "heading_field is required when the input geometries are points"
        )
    if heading_field is not None and not sft.has(heading_field):
        raise ValueError(f"heading field '{heading_field}' does not exist")

    # one store query over buffered envelopes. Segments chunk into at most
    # _MAX_ENVELOPES boxes (vectorized min/max reduce per chunk): a 50k-
    # vertex GPS track must not become a 50k-term Or filter, and route
    # segments are consecutive so chunk envelopes stay tight.
    lo = np.minimum(segs_a, segs_b)
    hi = np.maximum(segs_a, segs_b)
    s = len(lo)
    per = -(-s // _MAX_ENVELOPES)
    pad = per * _MAX_ENVELOPES - s
    if pad:
        lo = np.concatenate([lo, np.repeat(lo[-1:], pad, axis=0)])
        hi = np.concatenate([hi, np.repeat(hi[-1:], pad, axis=0)])
    clo = lo.reshape(-1, per, 2).min(axis=1)  # [chunks, 2]
    chi = hi.reshape(-1, per, 2).max(axis=1)
    degs = np.array([
        _meters_to_degrees(buffer_m, float(max(abs(a), abs(b))))
        for a, b in zip(clo[:, 1], chi[:, 1])
    ])
    boxes = [
        wrap_box_filter(
            geom, clo[i, 0] - degs[i], clo[i, 1] - degs[i],
            chi[i, 0] + degs[i], chi[i, 1] + degs[i],
        )
        for i in range(len(clo))
    ]
    spatial: Filter = boxes[0] if len(boxes) == 1 else Or(tuple(boxes))
    f = spatial if isinstance(filter, Include) else And((spatial, filter))
    out = store.query(type_name, f)
    if len(out) == 0:
        return out

    px, py, feat_heading = _comparison_points(out, geom, heading_field)

    # closest segment per candidate (chunked [n, s] distance matrix)
    n, s = len(px), len(segs_a)
    best_d = np.full(n, np.inf)
    best_bearing = np.zeros(n)
    rows_per = max(1, _CHUNK // s)
    for i in range(0, n, rows_per):
        j = slice(i, min(i + rows_per, n))
        d, bearing = _point_segment_distances(px[j], py[j], segs_a, segs_b)
        k = np.argmin(d, axis=1)
        rng = np.arange(len(k))
        best_d[j] = d[rng, k]
        best_bearing[j] = bearing[rng, k]

    keep = best_d <= buffer_m
    diff = heading_diff(best_bearing, feat_heading)
    match = diff <= heading_threshold_deg
    if bidirectional:
        match |= np.abs(diff - 180.0) <= heading_threshold_deg
    return out.mask(keep & match)


def _route_segments(routes):
    """Routes -> (starts [s, 2], ends [s, 2]) over all segments."""
    if isinstance(routes, (geo.LineString, str)) or (
        isinstance(routes, np.ndarray) and routes.ndim == 2
    ):
        routes = [routes]
    a_parts, b_parts = [], []
    for r in routes:
        c = _route_coords(r)
        a_parts.append(c[:-1])
        b_parts.append(c[1:])
    if not a_parts:
        return np.zeros((0, 2)), np.zeros((0, 2))
    return np.concatenate(a_parts), np.concatenate(b_parts)


def _comparison_points(fc: FeatureCollection, geom: str, heading_field):
    """(x, y, heading) per candidate: points use (x, y) + heading column;
    linestrings use their end point + last-segment bearing."""
    col = fc.columns[geom]
    from geomesa_tpu.filter.predicates import PointColumn

    if isinstance(col, PointColumn):
        px, py = np.asarray(col.x, np.float64), np.asarray(col.y, np.float64)
        heading = np.asarray(fc.columns[heading_field], dtype=np.float64)
        return px, py, heading
    n = len(fc)
    px = np.empty(n)
    py = np.empty(n)
    heading = np.empty(n)
    for i in range(n):
        g = col.geometry(i)
        if not isinstance(g, geo.LineString) or len(g.coords) < 2:
            raise ValueError("route matching requires Point or LineString features")
        c = np.asarray(g.coords, dtype=np.float64)
        px[i], py[i] = c[-1]
        if heading_field is not None:
            heading[i] = float(fc.columns[heading_field][i])
        else:
            heading[i] = _segment_bearings(
                c[-2:-1], c[-1:], np.array([c[-1, 1]])
            )[0]
    return px, py, heading


def _point_segment_distances(px, py, a, b):
    """([n] points, [s] segments) -> (distance_m [n, s], bearing [n, s]).

    Local equirectangular projection anchored per candidate point: lon is
    scaled by cos(lat) so both distance and the projected nearest point
    are in meters."""
    lat_scale = np.cos(np.radians(py))[:, None]  # [n, 1]
    ax = (a[None, :, 0] - px[:, None]) * lat_scale * METERS_PER_DEGREE
    ay = (a[None, :, 1] - py[:, None]) * METERS_PER_DEGREE
    bx = (b[None, :, 0] - px[:, None]) * lat_scale * METERS_PER_DEGREE
    by = (b[None, :, 1] - py[:, None]) * METERS_PER_DEGREE
    dx = bx - ax
    dy = by - ay
    seg_len2 = dx * dx + dy * dy
    # projection parameter of the origin (the candidate) onto each segment
    t = np.clip(-(ax * dx + ay * dy) / np.maximum(seg_len2, 1e-12), 0.0, 1.0)
    cx = ax + t * dx
    cy = ay + t * dy
    d = np.sqrt(cx * cx + cy * cy)
    bearing = (np.degrees(np.arctan2(dx, dy)) + 360.0) % 360.0
    return d, bearing
