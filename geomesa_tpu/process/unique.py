"""Unique-value enumeration over query results.

Reference: UniqueProcess (/root/reference/geomesa-process/src/main/scala/
org/locationtech/geomesa/process/analytic/UniqueProcess.scala) — distinct
values of one attribute, optionally with counts and sorting. Columnar
np.unique replaces the reference's per-feature visitor.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.filter.predicates import Filter, Include


def unique_values(
    store,
    type_name: str,
    attribute: str,
    filter: "Filter | str" = Include(),
    sort_by_count: bool = False,
) -> list[tuple]:
    """[(value, count)] of distinct attribute values among matching rows."""
    out = store.query(type_name, filter)
    if len(out) == 0:
        return []
    vals, cnts = np.unique(np.asarray(out.columns[attribute]), return_counts=True)
    pairs = [
        (v.item() if hasattr(v, "item") else v, int(c)) for v, c in zip(vals, cnts)
    ]
    if sort_by_count:
        pairs.sort(key=lambda p: -p[1])
    return pairs
