"""Analytics processes: the WPS-process tier of the reference
(geomesa-process, SURVEY.md §2.5) re-based on the DataStore query path.

Each process composes planner queries with vectorized post-compute:
k-nearest-neighbour search (single + pipelined batch), proximity and
route search, tube (spatio-temporal corridor) select, unique-value
enumeration, attribute joins, track transforms (point2point,
track_label, date_offset), BIN/Arrow conversion, and thin
query/sampling/minmax wrappers; density/stats wrap the DataStore
push-downs directly. All window-building processes wrap the
antimeridian.

Proximity and tube select also come in STANDING form
(:func:`standing_proximity` / :func:`standing_tube`, round 14): instead
of one query over stored features, they register persistent
subscriptions on a LambdaStore's inverted SubscriptionIndex — every
arriving batch is matched and alerts deliver continuously
(docs/standing.md)."""

from geomesa_tpu.process.join import join_search
from geomesa_tpu.process.knn import knn_many, knn_search
from geomesa_tpu.process.proximity import proximity_search, standing_proximity
from geomesa_tpu.process.route import heading_diff, route_search
from geomesa_tpu.process.transforms import (
    arrow_conversion,
    bin_conversion,
    date_offset,
    minmax_process,
    point2point,
    query_process,
    sampling_process,
    track_label,
)
from geomesa_tpu.process.tube import standing_tube, tube_select
from geomesa_tpu.process.unique import unique_values

__all__ = [
    "arrow_conversion",
    "bin_conversion",
    "date_offset",
    "heading_diff",
    "join_search",
    "knn_many",
    "knn_search",
    "minmax_process",
    "point2point",
    "proximity_search",
    "query_process",
    "route_search",
    "sampling_process",
    "standing_proximity",
    "standing_tube",
    "track_label",
    "tube_select",
    "unique_values",
]
