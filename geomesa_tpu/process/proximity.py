"""Proximity search: features within a distance of a set of input points.

Reference: ProximitySearchProcess (/root/reference/geomesa-process/src/
main/scala/org/locationtech/geomesa/process/query/
ProximitySearchProcess.scala) — buffers each input geometry and unions the
results. Here: one store query over the union of buffered bboxes, then a
vectorized min-distance-to-any-input refinement.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.filter.predicates import And, Filter, Include, Or
from geomesa_tpu.process.knn import _meters_to_degrees, haversine_m, wrap_box_filter


def proximity_search(
    store,
    type_name: str,
    points: "np.ndarray | list",
    distance_m: float,
    filter: Filter = Include(),
) -> FeatureCollection:
    """Features within ``distance_m`` meters of any of the (x, y) points."""
    pts = np.asarray(points, dtype=np.float64).reshape(-1, 2)
    if len(pts) == 0:
        return _empty(store, type_name)
    sft = store.get_schema(type_name)
    geom = sft.geom_field
    boxes = [
        wrap_box_filter(
            geom,
            x - (deg := _meters_to_degrees(distance_m, y)), y - deg,
            x + deg, y + deg,
        )
        for x, y in pts
    ]
    spatial: Filter = boxes[0] if len(boxes) == 1 else Or(tuple(boxes))
    f = spatial if isinstance(filter, Include) else And((spatial, filter))
    out = store.query(type_name, f)
    if len(out) == 0:
        return out
    cx, cy = out.representative_xy()
    # [n, p] pairwise distances; keep rows within range of any input
    d = haversine_m(
        cx[:, None], cy[:, None], pts[None, :, 0], pts[None, :, 1]
    )
    return out.mask(d.min(axis=1) <= distance_m)


def _empty(store, type_name: str) -> FeatureCollection:
    return store.features(type_name).take(np.zeros(0, dtype=np.int64))


def standing_proximity(
    lam,
    sub_id: str,
    points: "np.ndarray | list",
    distance_m: float,
    attrs: "dict | None" = None,
):
    """:func:`proximity_search`, STANDING (docs/standing.md): instead of
    one query over stored features, register a persistent subscription
    on a :class:`~geomesa_tpu.streaming.LambdaStore` — every arriving
    batch routes through the inverted SubscriptionIndex and events
    within ``distance_m`` of any input point deliver alerts. Same
    refinement semantics as the one-shot process (haversine min-distance
    to any input). Returns the registered
    :class:`~geomesa_tpu.streaming.Subscription`."""
    from geomesa_tpu.streaming.standing import Subscription

    sub = Subscription(
        str(sub_id), "proximity",
        points=np.asarray(points, np.float64).reshape(-1, 2),
        distance_m=float(distance_m), attrs=dict(attrs or {}),
    )
    lam.subscribe(sub)
    return sub
