"""Proximity search: features within a distance of a set of input points.

Reference: ProximitySearchProcess (/root/reference/geomesa-process/src/
main/scala/org/locationtech/geomesa/process/query/
ProximitySearchProcess.scala) — buffers each input geometry and unions the
results. Here: one store query over the union of buffered bboxes, then a
vectorized min-distance-to-any-input refinement.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.filter.predicates import And, Filter, Include, Or
from geomesa_tpu.process.knn import _meters_to_degrees, haversine_m, wrap_box_filter


def proximity_search(
    store,
    type_name: str,
    points: "np.ndarray | list",
    distance_m: float,
    filter: Filter = Include(),
) -> FeatureCollection:
    """Features within ``distance_m`` meters of any of the (x, y) points."""
    pts = np.asarray(points, dtype=np.float64).reshape(-1, 2)
    if len(pts) == 0:
        return _empty(store, type_name)
    sft = store.get_schema(type_name)
    geom = sft.geom_field
    boxes = [
        wrap_box_filter(
            geom,
            x - (deg := _meters_to_degrees(distance_m, y)), y - deg,
            x + deg, y + deg,
        )
        for x, y in pts
    ]
    spatial: Filter = boxes[0] if len(boxes) == 1 else Or(tuple(boxes))
    f = spatial if isinstance(filter, Include) else And((spatial, filter))
    out = store.query(type_name, f)
    if len(out) == 0:
        return out
    cx, cy = out.representative_xy()
    # [n, p] pairwise distances; keep rows within range of any input
    d = haversine_m(
        cx[:, None], cy[:, None], pts[None, :, 0], pts[None, :, 1]
    )
    return out.mask(d.min(axis=1) <= distance_m)


def _empty(store, type_name: str) -> FeatureCollection:
    return store.features(type_name).take(np.zeros(0, dtype=np.int64))
