"""Transform/analytic processes over feature collections.

Reference: geomesa-process-vector's collection transforms —
Point2PointProcess (/root/reference/geomesa-process/geomesa-process-vector/
src/main/scala/org/locationtech/geomesa/process/analytic/
Point2PointProcess.scala:36-116), TrackLabelProcess (analytic/
TrackLabelProcess.scala:27-60), DateOffsetProcess (transform/
DateOffsetProcess.scala:26-60), BinConversionProcess /
ArrowConversionProcess (transform/). The per-feature iterator pipelines
become grouped numpy passes: one lexsort by (group, time) and boundary
arithmetic over the sorted runs.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu import geometry as geo
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.filter.predicates import PointColumn
from geomesa_tpu.sft import FeatureType


def _group_sorted(fc: FeatureCollection, group_field: str, sort_field: str):
    """(order, starts): lexsort of rows by (group, sort) and the start
    offsets of each group run in that order."""
    g = np.asarray(fc.columns[group_field])
    s = np.asarray(fc.columns[sort_field])
    order = np.lexsort((s, g))
    gs = g[order]
    starts = np.concatenate(
        [[0], np.flatnonzero(gs[1:] != gs[:-1]) + 1, [len(gs)]]
    )
    return order, starts


def track_label(
    fc: FeatureCollection, track_field: str, dtg_field: "str | None" = None
) -> FeatureCollection:
    """One feature per track — the latest by ``dtg_field`` (or the last
    row in input order), for labelling (reference TrackLabelProcess)."""
    if len(fc) == 0:
        return fc
    # lexsort is stable, so sorting by (track, track) preserves input
    # order within each track — the dtg-less case needs no special path
    order, starts = _group_sorted(fc, track_field, dtg_field or track_field)
    last = order[starts[1:] - 1]
    return fc.take(np.sort(last))


def date_offset(
    fc: FeatureCollection, date_field: str, offset_ms: int
) -> FeatureCollection:
    """Shift a date column by ``offset_ms`` (reference DateOffsetProcess;
    the reference parses an ISO-8601 period — callers pass millis here)."""
    out = fc.take(np.arange(len(fc)))
    out.columns[date_field] = (
        np.asarray(out.columns[date_field], dtype=np.int64) + int(offset_ms)
    )
    return out


def point2point(
    fc: FeatureCollection,
    group_field: str,
    sort_field: str,
    min_points: int = 2,
    break_on_day: bool = False,
    filter_singular: bool = True,
) -> FeatureCollection:
    """Connect each group's time-ordered points into 2-point line segments
    (reference Point2PointProcess): output schema is
    ``*geom:LineString, <group>, <sort>_start:Date, <sort>_end:Date``,
    one feature per consecutive pair, ids ``<group>-<idx>``.

    ``min_points``: groups must have MORE than this many points (the
    reference's lengthCompare(minPoints) > 0). ``break_on_day`` splits
    runs at UTC day boundaries; ``filter_singular`` drops zero-length
    segments (both endpoints identical)."""
    col = fc.geom_column
    if not isinstance(col, PointColumn):
        raise ValueError("point2point requires point geometries")
    out_sft = FeatureType.from_spec(
        "point2point",
        f"*geom:LineString:srid=4326,{group_field}:String,"
        f"{sort_field}_start:Date,{sort_field}_end:Date",
    )
    if len(fc) == 0:
        return FeatureCollection.from_rows(out_sft, [])
    order, starts = _group_sorted(fc, group_field, sort_field)
    g = np.asarray(fc.columns[group_field])[order]
    t = np.asarray(fc.columns[sort_field], dtype=np.int64)[order]
    x = np.asarray(col.x, dtype=np.float64)[order]
    y = np.asarray(col.y, dtype=np.float64)[order]

    # pair i connects sorted rows i -> i+1; valid pairs stay inside one
    # group run of size > min_points (and one UTC day with break_on_day)
    n = len(g)
    valid = np.ones(max(n - 1, 0), dtype=bool)
    valid[starts[1:-1] - 1] = False  # pairs crossing group boundaries
    sizes = np.diff(starts)
    small = sizes <= min_points
    if small.any():
        drop = np.zeros(n, dtype=bool)
        for k in np.flatnonzero(small):
            drop[starts[k] : starts[k + 1]] = True
        valid &= ~(drop[:-1] | drop[1:])
    if break_on_day:
        day = t // 86_400_000
        valid &= day[:-1] == day[1:]
    if filter_singular:
        valid &= (x[:-1] != x[1:]) | (y[:-1] != y[1:])
    idx = np.flatnonzero(valid)
    if len(idx) == 0:
        return FeatureCollection.from_rows(out_sft, [])

    coords = np.empty((len(idx) * 2, 2), dtype=np.float64)
    coords[0::2, 0] = x[idx]
    coords[0::2, 1] = y[idx]
    coords[1::2, 0] = x[idx + 1]
    coords[1::2, 1] = y[idx + 1]
    two = np.arange(len(idx) + 1, dtype=np.int32)
    lo = np.nextafter(
        np.minimum(coords[0::2], coords[1::2]).astype(np.float32), -np.inf
    )
    hi = np.nextafter(
        np.maximum(coords[0::2], coords[1::2]).astype(np.float32), np.inf
    )
    lines = geo.PackedGeometryColumn(
        coords=coords,
        ring_offsets=two * 2,
        part_ring_offsets=two,
        geom_part_offsets=two,
        types=np.full(len(idx), geo.LINESTRING, dtype=np.int8),
        bboxes=np.concatenate([lo, hi], axis=1).astype(np.float32),
    )
    # per-group segment counter for the reference's "<group>-<idx>" ids
    grp = g[idx]
    seg_starts = np.concatenate(
        [[0], np.flatnonzero(grp[1:] != grp[:-1]) + 1]
    )
    within = np.arange(len(idx)) - np.repeat(seg_starts, np.diff(np.concatenate([seg_starts, [len(idx)]])))
    ids = [f"{v}-{i}" for v, i in zip(grp.tolist(), within.tolist())]
    return FeatureCollection.from_columns(
        out_sft,
        ids,
        {
            "geom": lines,
            group_field: grp.astype(str),
            f"{sort_field}_start": t[idx],
            f"{sort_field}_end": t[idx + 1],
        },
    )


def bin_conversion(
    fc: FeatureCollection,
    track_field: str,
    dtg_field: str,
    label_field: "str | None" = None,
    sort: bool = False,
) -> bytes:
    """Encode a collection to BIN records (reference
    BinConversionProcess; format utils/bin_format)."""
    from geomesa_tpu.utils import bin_format

    x, y = fc.representative_xy()
    return bin_format.encode(
        x, y,
        np.asarray(fc.columns[dtg_field], dtype=np.int64),
        np.asarray(fc.columns[track_field]),
        label=None if label_field is None else np.asarray(fc.columns[label_field]),
        sort=sort,
    )


def arrow_conversion(fc: FeatureCollection, dictionary: bool = True) -> bytes:
    """Encode a collection to an Arrow IPC stream (reference
    ArrowConversionProcess; io/arrow dictionary-encoded batches)."""
    from geomesa_tpu.io.arrow import arrow_stream

    return arrow_stream(fc, dictionary=dictionary)


def query_process(store, type_name: str, f, limit=None) -> FeatureCollection:
    """Thin QueryProcess analogue (reference query/QueryProcess.scala):
    evaluate a filter against a store through the planner."""
    return store.query(type_name, f, limit=limit)


def sampling_process(
    fc: FeatureCollection, fraction: float, threading_field: "str | None" = None
) -> FeatureCollection:
    """SamplingProcess analogue (reference analytic/SamplingProcess.scala):
    per-group deterministic thinning via FeatureCollection.sample."""
    return fc.sample(fraction, threading_field)


def minmax_process(store, type_name: str, attribute: str, cql="INCLUDE"):
    """MinMaxProcess analogue (reference analytic/MinMaxProcess.scala):
    (min, max) of an attribute under a filter. Served from the stats
    sketches only when the filter is INCLUDE AND no visibility or
    interceptor could hide rows (sketches see every row — the same gate
    every aggregate fast path in the store applies); exact via the
    planner otherwise."""
    from geomesa_tpu.filter import ecql
    from geomesa_tpu.filter.predicates import Include

    f = ecql.parse(cql) if isinstance(cql, str) else cql
    sketch_ok = (
        isinstance(f, Include)
        and not store._vis_active(type_name)
        and not store.interceptors
    )
    if sketch_ok:
        stats = store.stats_for(type_name)
        if stats is not None:
            b = stats.attribute_bounds(attribute)
            if b is not None:
                return b
    # exact path through the Stat DSL (handles geometry/point columns —
    # a bare np.min over a PointColumn would raise)
    results = store.stats_query(type_name, f"MinMax({attribute})", f)
    return results[0].bounds
