"""k-nearest-neighbour search over an indexed store.

Reference: KNearestNeighborSearchProcess (/root/reference/geomesa-process/
src/main/scala/org/locationtech/geomesa/process/query/
KNearestNeighborSearchProcess.scala:40) — seeds a search envelope from an
estimated distance, queries the store, and widens the window until k
neighbours are found or the cutoff is hit. Same expanding-window protocol
here; per-candidate distances are one vectorized haversine over the
gathered batch rather than a per-feature priority queue.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.filter.predicates import And, BBox, Filter, Include

EARTH_RADIUS_M = 6_371_000.0


def haversine_m(lon1, lat1, lon2, lat2) -> np.ndarray:
    """Great-circle distance in meters (vectorized)."""
    lon1, lat1, lon2, lat2 = (np.radians(np.asarray(v, dtype=np.float64)) for v in (lon1, lat1, lon2, lat2))
    dlon = lon2 - lon1
    dlat = lat2 - lat1
    a = np.sin(dlat / 2) ** 2 + np.cos(lat1) * np.cos(lat2) * np.sin(dlon / 2) ** 2
    return 2 * EARTH_RADIUS_M * np.arcsin(np.minimum(1.0, np.sqrt(a)))


def _meters_to_degrees(m: float, lat: float) -> float:
    """Conservative (over-wide) degree radius for a meter distance."""
    lat_deg = m / 111_320.0
    lon_deg = lat_deg / max(0.01, np.cos(np.radians(min(abs(lat), 89.0))))
    return float(max(lat_deg, lon_deg))


def knn_search(
    store,
    type_name: str,
    x: float,
    y: float,
    k: int,
    estimated_distance_m: float = 10_000.0,
    max_distance_m: float = 1_000_000.0,
    filter: Filter = Include(),
) -> FeatureCollection:
    """The k features nearest (x, y), ordered nearest-first.

    Expands the query window from ``estimated_distance_m`` by doubling
    until k in-radius hits exist or ``max_distance_m`` is reached
    (reference's KNNQuery window protocol).
    """
    sft = store.get_schema(type_name)
    geom = sft.geom_field
    # clamp to a positive start: radius 0 would never grow (min(0*2, max))
    radius = min(max(float(estimated_distance_m), 1.0), float(max_distance_m))
    while True:
        deg = _meters_to_degrees(radius, y)
        box = BBox(geom, x - deg, max(y - deg, -90.0), x + deg, min(y + deg, 90.0))
        f = box if isinstance(filter, Include) else And((box, filter))
        out = store.query(type_name, f)
        if len(out):
            cx, cy = out.representative_xy()
            d = haversine_m(x, y, cx, cy)
            in_radius = d <= radius
            if in_radius.sum() >= k or radius >= max_distance_m:
                keep = np.nonzero(in_radius)[0]
                order = keep[np.argsort(d[keep], kind="stable")][:k]
                return out.take(order)
        elif radius >= max_distance_m:
            return out
        radius = min(radius * 2.0, max_distance_m)
