"""k-nearest-neighbour search over an indexed store.

Reference: KNearestNeighborSearchProcess (/root/reference/geomesa-process/
src/main/scala/org/locationtech/geomesa/process/query/
KNearestNeighborSearchProcess.scala:40) — seeds a search envelope from an
estimated distance, queries the store, and widens the window until k
neighbours are found or the cutoff is hit. Same expanding-window protocol
here; per-candidate distances are one vectorized haversine over the
gathered batch rather than a per-feature priority queue.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.filter.predicates import And, Filter, Include

EARTH_RADIUS_M = 6_371_000.0


def haversine_m(lon1, lat1, lon2, lat2) -> np.ndarray:
    """Great-circle distance in meters (vectorized)."""
    lon1, lat1, lon2, lat2 = (np.radians(np.asarray(v, dtype=np.float64)) for v in (lon1, lat1, lon2, lat2))
    dlon = lon2 - lon1
    dlat = lat2 - lat1
    a = np.sin(dlat / 2) ** 2 + np.cos(lat1) * np.cos(lat2) * np.sin(dlon / 2) ** 2
    return 2 * EARTH_RADIUS_M * np.arcsin(np.minimum(1.0, np.sqrt(a)))


METERS_PER_DEGREE = 111_320.0  # one degree of latitude (~also longitude at equator)


def _meters_to_degrees(m: float, lat: float) -> float:
    """Conservative (over-wide) degree radius for a meter distance."""
    lat_deg = m / METERS_PER_DEGREE
    lon_deg = lat_deg / max(0.01, np.cos(np.radians(min(abs(lat), 89.0))))
    return float(max(lat_deg, lon_deg))


def _degrees_to_meters(deg: float, lat: float) -> float:
    """Meters spanned by a longitude extent of ``deg`` at ``lat`` (the
    inverse direction of _meters_to_degrees, same constants)."""
    return float(
        deg * METERS_PER_DEGREE * max(0.01, np.cos(np.radians(min(abs(lat), 89.0))))
    )


from geomesa_tpu.filter.predicates import wrap_box as wrap_box_filter  # noqa: E402
# (one wrapping implementation — filter.predicates.wrap_box — shared by
# the kNN/proximity/route window builders and the planner's
# normalize_antimeridian rewrite)


def _window_filter(geom: str, x: float, y: float, deg: float) -> Filter:
    return wrap_box_filter(geom, x - deg, y - deg, x + deg, y + deg)


def knn_search(
    store,
    type_name: str,
    x: float,
    y: float,
    k: int,
    estimated_distance_m: "float | None" = None,
    max_distance_m: float = 1_000_000.0,
    filter: Filter = Include(),
) -> FeatureCollection:
    """The k features nearest (x, y), ordered nearest-first.

    Expands the query window from ``estimated_distance_m`` by doubling
    until k in-radius hits exist or ``max_distance_m`` is reached
    (reference's KNNQuery window protocol). With ``estimated_distance_m``
    None, the start radius comes from the store's statistics — mean point
    density refined by the local histogram probe (every extra expansion
    round costs a full store query). One implementation serves the
    single-point and batched forms: this is ``knn_many`` with one point."""
    return knn_many(
        store, type_name, [(x, y)], k,
        estimated_distance_m=estimated_distance_m,
        max_distance_m=max_distance_m, filter=filter,
    )[0]


def knn_many(
    store,
    type_name: str,
    points,
    k: int,
    estimated_distance_m: "float | None" = None,
    max_distance_m: float = 1_000_000.0,
    filter: Filter = Include(),
) -> list[FeatureCollection]:
    """k nearest neighbours for MANY query points with pipelined rounds.

    Each round plans every still-unsatisfied query's window, submits all
    device scans before pulling any result (planner.submit), then doubles
    the radius only for queries short of k — so a batch of Q queries pays
    ~max_rounds pipelined sweeps instead of Q x rounds sequential device
    round-trips. Results are identical to per-point :func:`knn_search`."""
    pts = np.asarray(points, dtype=np.float64).reshape(-1, 2)
    sft = store.get_schema(type_name)
    geom = sft.geom_field
    out: list = [None] * len(pts)
    radii = np.empty(len(pts))
    for i, (x, y) in enumerate(pts):
        r = (
            _estimate_radius_m(store, type_name, k, float(x), float(y), max_distance_m)
            if estimated_distance_m is None
            else float(estimated_distance_m)
        )
        radii[i] = min(max(r, 1.0), float(max_distance_m))
    # speculative wide-window rounds: each pending query scans ONE window
    # at 4x its radius estimate per round — the estimate radius resolves
    # from the SAME result (the degree window is conservatively over-wide,
    # so every point within the estimate radius lies inside the estimate's
    # bbox, which the 4x bbox contains; filtering the wide result by
    # distance is therefore bit-equivalent to scanning the narrow window).
    # Rounds 5-10 dispatched BOTH windows speculatively; halving the
    # per-query dispatches this way is what lets a whole batch's window
    # probes pack into fewer fused block_scan_multi chunks (and halves
    # the plan/decomposition host work per round). A sketch
    # under-estimate still costs zero extra device round-trips — the 4x
    # acceptance check reads the already-pulled result. Radius jumps 16x
    # between rounds (a miss at 4x means the estimate was far off).
    SPEC = 4.0

    def _plan(i: int, r: float):
        x, y = pts[i]
        deg = _meters_to_degrees(r, float(y))
        box = _window_filter(geom, float(x), float(y), deg)
        f = box if isinstance(filter, Include) else And((box, filter))
        return store.planner.plan(type_name, f)

    def _top_k(res, d, in_radius):
        """The k nearest among ``in_radius`` rows, nearest-first — ties
        resolved by original position exactly like a full stable argsort
        (the argpartition prefilter keeps every kth-distance tie, so the
        stable sort of the survivors selects the same rows)."""
        sel = np.nonzero(in_radius)[0]
        ds = d[sel]
        if len(sel) > 4 * k + 64:
            kth = np.partition(ds, k - 1)[k - 1]
            sub = np.nonzero(ds <= kth)[0]
            order = sel[sub[np.argsort(ds[sub], kind="stable")]][:k]
        else:
            order = sel[np.argsort(ds, kind="stable")][:k]
        return res.take(order)

    def _resolve(i: int, res, radii_try):
        """First radius in ``radii_try`` (ascending) holding k-or-more
        hits -> its k nearest; else None (miss -> expand)."""
        x, y = pts[i]
        if len(res):
            cx, cy = res.representative_xy()
            d = haversine_m(x, y, cx, cy)
            for r in radii_try:
                in_radius = d <= r
                if in_radius.sum() >= k or r >= max_distance_m:
                    return _top_k(res, d, in_radius)
        elif radii_try[-1] >= max_distance_m:
            return res
        return None

    pending = list(range(len(pts)))
    while pending:
        # every pending query's window goes through ONE submit_many:
        # scans sharing the index fuse into a single kernel dispatch per
        # variant group (planner.submit_many -> table.scan_submit_many)
        wides = [min(float(radii[i]) * SPEC, max_distance_m) for i in pending]
        fins = store.planner.submit_many(
            [_plan(i, w) for i, w in zip(pending, wides)], hints=None
        )
        nxt = []
        for i, w, fin in zip(pending, wides, fins):
            r = float(radii[i])
            got = _resolve(i, fin(), [r, w] if w > r else [r])
            if got is not None:
                out[i] = got
                continue
            radii[i] = min(float(radii[i]) * SPEC * SPEC, max_distance_m)
            nxt.append(i)
        pending = nxt
    return out


def _estimate_radius_m(
    store,
    type_name: str,
    k: int,
    x: float,
    y: float,
    max_m: float,
    fallback: float = 10_000.0,
) -> float:
    """Start radius for the expanding-window search.

    Two tiers (each device-free):
    1. global mean density over the stats envelope — r such that a circle
       holds ~4k points under uniform density (4x cushion for clustering);
    2. *local* refinement against the Z-histogram sketch (the same
       StatsBasedEstimator tier the planner's cost model uses): grow the
       window host-side until the sketch predicts >= 4k hits near THIS
       query point. Every avoided doubling round saves a full store query
       (one device round-trip), which dominates kNN latency on sparse
       regions — global density badly underestimates the radius there."""
    import math

    stats = store.stats_for(type_name)
    if stats is None:
        return fallback
    geom = store.get_schema(type_name).geom_field
    bx = stats.attribute_bounds(f"{geom}.x")
    by = stats.attribute_bounds(f"{geom}.y")
    n = stats.total_count()
    if not n or bx is None or by is None:
        return fallback
    x0, x1 = float(bx[0]), float(bx[1])
    y0, y1 = float(by[0]), float(by[1])
    mid_lat = (y0 + y1) / 2.0
    area_m2 = _degrees_to_meters(max(x1 - x0, 1e-9), mid_lat) * (
        max(y1 - y0, 1e-9) * METERS_PER_DEGREE
    )
    density = n / area_m2  # points per m^2
    if density <= 0:
        return fallback
    r = math.sqrt(4.0 * k / (math.pi * density))
    # floor: a tight cluster yields a microscopic r, and a query point
    # outside the cluster would then pay many doubling rounds (each a full
    # store query) — never start below a tenth of the old fixed default
    r = max(r, fallback / 10.0)
    return _refine_radius_local(stats, geom, k, x, y, r, max_m)


def _refine_radius_local(
    stats, geom: str, k: int, x: float, y: float, r: float, max_m: float
) -> float:
    """Grow ``r`` until the marginal-histogram estimator predicts ~4k
    hits in the window around (x, y). Sketch-only: no device work, no
    range decomposition — each probe is two histogram range sums."""
    target = max(4 * k, 64)
    while r < max_m:
        deg = _meters_to_degrees(r, y)
        est = stats.estimate_bbox(
            geom, x - deg, max(y - deg, -90.0), x + deg, min(y + deg, 90.0)
        )
        if est is None or est >= target:
            break
        r = min(r * 2.0, max_m)
    return r
