"""Coordinate reference systems: 4326 <-> 3857 reprojection.

The reference carries full GeoTools CRS machinery (reprojection hints set
in geomesa-index-api/.../planning/QueryPlanner.scala:292, BBOX CRS
arguments through the filter stack). The store here is EPSG:4326-native
end to end — the curve math, device columns and predicates all assume
lon/lat degrees — so CRS support is a boundary concern: query geometry
arguments in a supported foreign CRS reproject to 4326 before planning,
and a ``reproject`` query hint transforms result geometries after the
scan. Unsupported CRSs raise instead of being silently ignored.

Supported: EPSG:4326 (and its aliases CRS:84 / OGC:CRS84 / WGS84 —
axis order here is always lon/lat) and EPSG:3857 (spherical web
mercator; the numpy closed forms below, radius 6378137 m).
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu import geometry as geo

_R = 6378137.0  # web-mercator sphere radius (meters)
# latitude bound where mercator y is finite: atan(sinh(pi)) in degrees
MAX_LAT_3857 = 85.05112877980659

_ALIASES_4326 = {
    "EPSG:4326", "4326", "CRS:84", "OGC:CRS84", "CRS84", "WGS84",
    "URN:OGC:DEF:CRS:EPSG::4326", "URN:OGC:DEF:CRS:OGC:1.3:CRS84",
}
_ALIASES_3857 = {
    "EPSG:3857", "3857", "EPSG:900913", "900913",
    "URN:OGC:DEF:CRS:EPSG::3857",
}


def normalize_crs(crs: str) -> str:
    """Canonical "EPSG:4326" / "EPSG:3857"; raises on unsupported CRSs
    (reference behavior: an unknown CRS is an error, never a silent
    identity)."""
    key = str(crs).strip().upper().replace(" ", "")
    if key in _ALIASES_4326:
        return "EPSG:4326"
    if key in _ALIASES_3857:
        return "EPSG:3857"
    raise ValueError(
        f"unsupported CRS {crs!r}: supported are EPSG:4326 (CRS:84) and "
        "EPSG:3857"
    )


def to_4326(x, y, crs: str):
    """Coordinates in ``crs`` -> lon/lat degrees (vectorized)."""
    if normalize_crs(crs) == "EPSG:4326":
        return np.asarray(x, np.float64), np.asarray(y, np.float64)
    x = np.asarray(x, np.float64)
    y = np.asarray(y, np.float64)
    lon = np.degrees(x / _R)
    lat = np.degrees(2.0 * np.arctan(np.exp(y / _R)) - np.pi / 2.0)
    return lon, lat


def from_4326(lon, lat, crs: str):
    """Lon/lat degrees -> coordinates in ``crs`` (vectorized). Latitudes
    are clamped to the mercator domain (|lat| <= ~85.05) the way web
    mercator implementations conventionally do."""
    if normalize_crs(crs) == "EPSG:4326":
        return np.asarray(lon, np.float64), np.asarray(lat, np.float64)
    lon = np.asarray(lon, np.float64)
    lat = np.clip(np.asarray(lat, np.float64), -MAX_LAT_3857, MAX_LAT_3857)
    x = _R * np.radians(lon)
    y = _R * np.log(np.tan(np.pi / 4.0 + np.radians(lat) / 2.0))
    return x, y


def bbox_to_4326(x0: float, y0: float, x1: float, y1: float, crs: str):
    """An axis-aligned box in ``crs`` -> the equivalent 4326 box. Exact
    for 3857: mercator is separable and monotone per axis, so corners map
    to corners."""
    lons, lats = to_4326(np.array([x0, x1]), np.array([y0, y1]), crs)
    return float(lons[0]), float(lats[0]), float(lons[1]), float(lats[1])


def transform_geometry(g: geo.Geometry, src: str, dst: str) -> geo.Geometry:
    """Reproject one geometry object src -> dst (both supported CRSs)."""
    src, dst = normalize_crs(src), normalize_crs(dst)
    if src == dst:
        return g

    def tx(c: np.ndarray) -> np.ndarray:
        lon, lat = (c[:, 0], c[:, 1]) if src == "EPSG:4326" else to_4326(
            c[:, 0], c[:, 1], src
        )
        x, y = (lon, lat) if dst == "EPSG:4326" else from_4326(lon, lat, dst)
        return np.stack([x, y], axis=1)

    if isinstance(g, geo.Point):
        p = tx(np.array([[g.x, g.y]]))
        return geo.Point(float(p[0, 0]), float(p[0, 1]))
    if isinstance(g, geo.LineString):
        return geo.LineString(tx(np.asarray(g.coords)))
    if isinstance(g, geo.Polygon):
        return geo.Polygon(
            tx(np.asarray(g.shell)), holes=[tx(np.asarray(h)) for h in g.holes]
        )
    if isinstance(g, (geo.MultiPoint, geo.MultiLineString, geo.MultiPolygon)):
        return type(g)([transform_geometry(p, src, dst) for p in g.parts])
    raise TypeError(f"cannot reproject {type(g).__name__}")


def reproject_collection(fc, crs: str):
    """A new FeatureCollection with the geometry column reprojected from
    4326 to ``crs`` (the reference's QueryPlanner reprojection stage).
    Scalar columns are shared, not copied."""
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.filter.predicates import PointColumn

    crs = normalize_crs(crs)
    if crs == "EPSG:4326" or fc.sft.geom_field is None:
        return fc
    col = fc.geom_column
    cols = dict(fc.columns)
    # stamp the output CRS on the derived SFT so CRS-labelling sinks
    # (GML srsName, shapefile prj) describe the coordinates they carry
    from dataclasses import replace as _replace

    from geomesa_tpu.sft import FeatureType

    attrs = []
    for a in fc.sft.attributes:
        if a.is_geometry:
            opts = dict(a.options)
            opts["srid"] = crs.split(":")[1]
            a = _replace(a, options=opts)
        attrs.append(a)
    user_data = dict(fc.sft.user_data)
    user_data["geomesa.crs"] = crs
    sft = FeatureType(fc.sft.name, attrs, user_data)
    if isinstance(col, PointColumn):
        x, y = from_4326(col.x, col.y, crs)
        cols[fc.sft.geom_field] = PointColumn(x, y)
    elif isinstance(col, geo.PackedGeometryColumn):
        c = np.asarray(col.coords, np.float64)
        x, y = from_4326(c[:, 0], c[:, 1], crs)
        coords = np.stack([x, y], axis=1)
        # mercator is monotone per axis: bbox corners map to corners
        bx0, by0 = from_4326(
            col.bboxes[:, 0].astype(np.float64),
            col.bboxes[:, 1].astype(np.float64), crs,
        )
        bx1, by1 = from_4326(
            col.bboxes[:, 2].astype(np.float64),
            col.bboxes[:, 3].astype(np.float64), crs,
        )
        bb = np.stack([bx0, by0, bx1, by1], axis=1).astype(np.float32)
        # keep the column's bbox invariant: one f32 ulp outward
        bb[:, :2] = np.nextafter(bb[:, :2], -np.inf)
        bb[:, 2:] = np.nextafter(bb[:, 2:], np.inf)
        out = geo.PackedGeometryColumn(
            coords, col.ring_offsets, col.part_ring_offsets,
            col.geom_part_offsets, col.types, bb,
        )
        # rectangles stay rectangles under the separable mercator map:
        # carry the box_info cache (with reprojected bounds) forward
        cached = getattr(col, "_box_info", None)
        if cached is not None:
            bmask, bounds = cached
            rx0, ry0 = from_4326(bounds[:, 0], bounds[:, 1], crs)
            rx1, ry1 = from_4326(bounds[:, 2], bounds[:, 3], crs)
            out._box_info = (bmask, np.stack([rx0, ry0, rx1, ry1], axis=1))
            out._uniform_rect = getattr(col, "_uniform_rect", False)
        cols[fc.sft.geom_field] = out
    return FeatureCollection(sft, fc.ids, cols)
