"""S2: Hilbert curve on the quadratically-projected unit cube.

Functional parity with the reference's S2SFC (/root/reference/geomesa-z3/
src/main/scala/org/locationtech/geomesa/curve/S2SFC.scala:23-60, which
wraps com.google.common.geometry): 64-bit cell ids laid out as
[3 face bits][2*level Hilbert position bits][1][trailing zeros], leaf
level 30. This is a from-scratch vectorized implementation of the same
curve structure (cube faces, quadratic ST projection, per-level Hilbert
orientation tables); ids are self-consistent within this package rather
than byte-compatible with Google's library (cross-compatibility is a
non-goal — ids never leave the store).

The covering (`ranges`) replaces S2RegionCoverer with a per-face quadtree
BFS classified in UV space: the query lat/lng box maps to one
*conservative superset* UV rectangle per face (exact monotone bounds on
equatorial faces, disk bounds on polar faces), so rectangle-vs-rectangle
classification is exact and the cover can never miss a true hit;
over-coverage is removed by the host refinement tier like every other
curve here.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from geomesa_tpu.curve.zranges import IndexRange

MAX_LEVEL = 30
_FACE_SHIFT = 2 * MAX_LEVEL + 1  # 61

# Hilbert orientation tables (standard S2 layout):
# position-in-parent -> (i, j) sub-cell, per orientation (swap|invert bits)
POS_TO_IJ = np.array(
    [[0, 1, 3, 2], [0, 2, 3, 1], [3, 2, 0, 1], [3, 1, 0, 2]], dtype=np.uint64
)
IJ_TO_POS = np.array(
    [[0, 1, 3, 2], [0, 3, 1, 2], [2, 3, 1, 0], [2, 1, 3, 0]], dtype=np.uint64
)
POS_TO_ORIENTATION = np.array([1, 0, 0, 3], dtype=np.uint64)

_U = np.uint64


# -- projection ----------------------------------------------------------

def _xyz_from_lonlat(lon, lat):
    lam = np.radians(np.asarray(lon, dtype=np.float64))
    phi = np.radians(np.asarray(lat, dtype=np.float64))
    cp = np.cos(phi)
    return cp * np.cos(lam), cp * np.sin(lam), np.sin(phi)


def _face_from_xyz(x, y, z):
    ax, ay, az = np.abs(x), np.abs(y), np.abs(z)
    face = np.where(ax >= ay, np.where(ax >= az, 0, 2), np.where(ay >= az, 1, 2))
    face = face + np.where(
        np.choose(face, [x, y, z]) < 0, 3, 0
    )
    return face.astype(np.int64)


def _uv_from_xyz(face, x, y, z):
    u = np.empty_like(x)
    v = np.empty_like(x)
    # canonical face->(u, v) with the TRUE (possibly negative) denominator
    for f, (ue, ve, de) in enumerate(
        [
            (lambda: y, lambda: z, lambda: x),  # 0: +x  u=y/x   v=z/x
            (lambda: -x, lambda: z, lambda: y),  # 1: +y  u=-x/y  v=z/y
            (lambda: -x, lambda: -y, lambda: z),  # 2: +z  u=-x/z  v=-y/z
            (lambda: z, lambda: y, lambda: x),  # 3: -x  u=z/x   v=y/x
            (lambda: z, lambda: -x, lambda: y),  # 4: -y  u=z/y   v=-x/y
            (lambda: -y, lambda: -x, lambda: z),  # 5: -z  u=-y/z  v=-x/z
        ]
    ):
        m = face == f
        if m.any():
            d = de()[m]
            u[m] = ue()[m] / d
            v[m] = ve()[m] / d
    return u, v


def _st_from_uv(u):
    """Quadratic projection (S2's default ST transform)."""
    s = 0.5 * np.sqrt(1.0 + 3.0 * np.abs(u))
    return np.where(u >= 0, s, 1.0 - s)


def _uv_from_st(s):
    s = np.asarray(s, dtype=np.float64)
    return np.where(
        s >= 0.5, (1.0 / 3.0) * (4.0 * s * s - 1.0), (1.0 / 3.0) * (1.0 - 4.0 * (1.0 - s) ** 2)
    )


def _ij_from_st(s):
    return np.clip((s * (1 << MAX_LEVEL)).astype(np.int64), 0, (1 << MAX_LEVEL) - 1)


# -- cell ids ------------------------------------------------------------

def cell_id_from_lonlat(lon, lat, level: int = MAX_LEVEL) -> np.ndarray:
    """Leaf (or coarser) cell ids for lon/lat arrays (vectorized)."""
    x, y, z = _xyz_from_lonlat(lon, lat)
    face = _face_from_xyz(x, y, z)
    u, v = _uv_from_xyz(face, x, y, z)
    i = _ij_from_st(_st_from_uv(u)).astype(np.uint64)
    j = _ij_from_st(_st_from_uv(v)).astype(np.uint64)
    return cell_id_from_face_ij(face.astype(np.uint64), i, j, level)


def cell_id_from_face_ij(face, i, j, level: int = MAX_LEVEL) -> np.ndarray:
    """Hilbert position encoding: 30-step orientation walk (vectorized)."""
    face = np.asarray(face, dtype=np.uint64)
    i = np.asarray(i, dtype=np.uint64)
    j = np.asarray(j, dtype=np.uint64)
    o = face & _U(1)  # initial orientation: swap bit from the face
    pos = np.zeros_like(face)
    for k in range(level):
        shift = _U(MAX_LEVEL - 1 - k)
        ib = (i >> shift) & _U(1)
        jb = (j >> shift) & _U(1)
        ij = (ib << _U(1)) | jb
        p = IJ_TO_POS[o, ij]
        pos = (pos << _U(2)) | p
        o = o ^ POS_TO_ORIENTATION[p]
    lsb = _U(1) << _U(2 * (MAX_LEVEL - level))
    return (face << _U(_FACE_SHIFT)) | ((pos << _U(1)) * lsb) | lsb


def cell_range(cell: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """[range_min, range_max] of leaf ids under a cell (S2CellId.rangeMin/Max)."""
    cell = np.asarray(cell, dtype=np.uint64)
    lsb = cell & (~cell + _U(1))
    return cell - (lsb - _U(1)), cell + (lsb - _U(1))


def cell_center_lonlat(cell) -> tuple[np.ndarray, np.ndarray]:
    """Cell center (lon, lat) — the curve inversion (reference invert)."""
    cell = np.asarray(np.atleast_1d(cell), dtype=np.uint64)
    face = (cell >> _U(_FACE_SHIFT)).astype(np.int64)
    lsb = cell & (~cell + _U(1))
    level = MAX_LEVEL - ((np.log2(lsb.astype(np.float64))).astype(np.int64)) // 2
    i = np.zeros(len(cell), dtype=np.uint64)
    j = np.zeros(len(cell), dtype=np.uint64)
    o = (cell >> _U(_FACE_SHIFT)) & _U(1)
    for k in range(MAX_LEVEL):
        active = k < level
        shift = _U(2 * (MAX_LEVEL - 1 - k) + 1)
        p = (cell >> shift) & _U(3)
        ij = POS_TO_IJ[o, p]
        bit = _U(MAX_LEVEL - 1 - k)
        i = np.where(active, i | ((ij >> _U(1)) << bit), i)
        j = np.where(active, j | ((ij & _U(1)) << bit), j)
        o = np.where(active, o ^ POS_TO_ORIENTATION[p], o)
    # center of the cell in ST space
    size = (_U(1) << (_U(MAX_LEVEL) - level.astype(np.uint64))).astype(np.float64)
    s = (i.astype(np.float64) + size / 2.0) / (1 << MAX_LEVEL)
    t = (j.astype(np.float64) + size / 2.0) / (1 << MAX_LEVEL)
    u = _uv_from_st(s)
    v = _uv_from_st(t)
    x, y, z = _xyz_from_face_uv(face, u, v)
    lon = np.degrees(np.arctan2(y, x))
    lat = np.degrees(np.arctan2(z, np.hypot(x, y)))
    return lon, lat


def _xyz_from_face_uv(face, u, v):
    x = np.empty_like(u)
    y = np.empty_like(u)
    z = np.empty_like(u)
    specs = [
        lambda u, v: (np.ones_like(u), u, v),  # 0: +x
        lambda u, v: (-u, np.ones_like(u), v),  # 1: +y
        lambda u, v: (-u, -v, np.ones_like(u)),  # 2: +z
        lambda u, v: (-np.ones_like(u), -v, -u),  # 3: -x  (inverse of uv 3)
        lambda u, v: (v, -np.ones_like(u), -u),  # 4: -y
        lambda u, v: (v, u, -np.ones_like(u)),  # 5: -z
    ]
    for f, fn in enumerate(specs):
        m = face == f
        if m.any():
            xf, yf, zf = fn(u[m], v[m])
            x[m], y[m], z[m] = xf, yf, zf
    n = np.sqrt(x * x + y * y + z * z)
    return x / n, y / n, z / n


# -- covering ------------------------------------------------------------

@dataclass
class _FaceRegion:
    """Conservative UV-rectangle superset of the query box on one face."""

    face: int
    u0: float
    v0: float
    u1: float
    v1: float


def _face_regions(xmin, ymin, xmax, ymax) -> list[_FaceRegion]:
    """Map a lat/lng box to conservative UV rectangles per face.

    Equatorial faces (0, 1, 3, 4 — centers at lng 0/90/180/-90): u is
    monotone in lng (u = tan(lng - center)); |v| <= tan(lat_max_abs) *
    sqrt(1 + u_max^2) bounds v exactly. Polar faces (2: north, 5: south):
    the box's polar cap portion lies within the disk r <= 1/tan(|lat|),
    bounded by its enclosing square.
    """
    out: list[_FaceRegion] = []
    if ymin <= 45.0 and ymax >= -45.0:  # equatorial faces reach |lat| <= 45
        # face axis orientation: on faces 0/1, u = tan(lng_rel) and
        # v = tan(lat) * sqrt(1 + u^2); on faces 3/4 the roles swap with a
        # sign flip: v = tan(lng_rel), u = -tan(lat) * sqrt(1 + v^2)
        centers = {0: 0.0, 1: 90.0, 3: 180.0, 4: -90.0}
        for face, center in centers.items():
            # signed lng offset of the box from the face center; a wide box
            # may wrap past +180 and re-enter at -180 — split the interval
            d0 = ((xmin - center + 180.0) % 360.0) - 180.0
            d1 = d0 + (xmax - xmin)
            pieces = [(d0, d1)] if d1 <= 180.0 else [(d0, 180.0), (-180.0, d1 - 360.0)]
            for p0, p1 in pieces:
                lo, hi = max(p0, -45.0), min(p1, 45.0)
                if hi < lo:
                    continue  # box misses this face's lng wedge
                a0, a1 = np.tan(np.radians(lo)), np.tan(np.radians(hi))
                amax = max(abs(a0), abs(a1))
                # conservative lat coordinate: scale >= 1 only widens the
                # bound in the direction away from zero
                scale = np.sqrt(1.0 + amax * amax)
                t_hi = np.tan(np.radians(min(ymax, 89.9999)))
                t_lo = np.tan(np.radians(max(ymin, -89.9999)))
                b1 = t_hi * (scale if t_hi >= 0 else 1.0)
                b0 = t_lo * (scale if t_lo <= 0 else 1.0)
                b0, b1 = float(np.clip(b0, -1, 1)), float(np.clip(b1, -1, 1))
                if face in (0, 1):
                    out.append(_FaceRegion(face, float(a0), b0, float(a1), b1))
                else:  # lng on v, negated lat on u
                    out.append(_FaceRegion(face, -b1, float(a0), -b0, float(a1)))
    # polar faces: a face point has |lat| >= atan(1/sqrt(2)) ~ 35.26 deg;
    # its radius r = hypot(u, v) = 1/tan(|lat|)
    if ymax >= 35.0:
        r = min(1.0 / np.tan(np.radians(max(ymin, 35.0))), 1.0) if ymin > 0 else 1.0
        out.append(_FaceRegion(2, -r, -r, r, r))
    if ymin <= -35.0:
        r = min(1.0 / np.tan(np.radians(-min(ymax, -35.0))), 1.0) if ymax < 0 else 1.0
        out.append(_FaceRegion(5, -r, -r, r, r))
    return out


class S2SFC:
    """S2 curve with region covering (reference S2SFC + S2RegionCoverer)."""

    def __init__(
        self,
        min_level: int = 0,
        max_level: int = MAX_LEVEL,
        level_mod: int = 1,
        max_cells: int = 2000,
    ):
        if not (0 <= min_level <= max_level <= MAX_LEVEL):
            raise ValueError(f"bad level range [{min_level}, {max_level}]")
        self.min_level = min_level
        self.max_level = max_level
        self.level_mod = max(1, level_mod)
        self.max_cells = max_cells

    def index(self, lon, lat) -> np.ndarray:
        """Leaf cell ids (reference S2SFC.index with lenient=true: clamp
        out-of-range coordinates, matching the z-curves' NormalizedDimension
        clamping so a mixed-index write can't fail halfway through)."""
        lon = np.clip(np.asarray(lon, dtype=np.float64), -180.0, 180.0)
        lat = np.clip(np.asarray(lat, dtype=np.float64), -90.0, 90.0)
        return cell_id_from_lonlat(lon, lat)

    def invert(self, cell) -> tuple[np.ndarray, np.ndarray]:
        return cell_center_lonlat(cell)

    def ranges(self, bounds) -> list[IndexRange]:
        """Covering leaf-id ranges for lat/lng boxes (reference ranges)."""
        spans: list[tuple[int, int]] = []
        regions: list[_FaceRegion] = []
        for (xmin, ymin, xmax, ymax) in bounds:
            if xmin > xmax or ymin > ymax:
                raise ValueError(f"inverted bbox: {(xmin, ymin, xmax, ymax)}")
            regions.extend(_face_regions(xmin, ymin, xmax, ymax))
        budget = max(4, self.max_cells // max(1, len(regions)))
        for region in regions:
            self._cover_face(region, spans, budget)
        if not spans:
            return []
        spans.sort()
        merged: list[list[int]] = []
        for lo, hi in spans:
            if merged and lo <= merged[-1][1] + 1:
                merged[-1][1] = max(merged[-1][1], hi)
            else:
                merged.append([lo, hi])
        return [IndexRange(lo, hi, contained=False) for lo, hi in merged]

    def _cover_face(self, region: _FaceRegion, out: list, budget: int) -> None:
        """BFS quadtree cover of one face's UV rectangle, bounded by
        ``budget`` emitted cells (the S2RegionCoverer maxCells analogue:
        when refining would blow the budget, the frontier emits coarse).

        ``level_mod`` shapes only which levels may *stop early* when a cell
        is contained; the emitted output is id ranges, so unions at
        non-conforming levels are not needed (unlike the reference's cell
        unions).
        """
        face = region.face
        # frontier: (level, pos_prefix, orientation, i0, j0)
        frontier = [(0, 0, face & 1, 0, 0)]
        emitted = 0
        while frontier:
            keep = []
            for node in frontier:
                (level, pos, o, i0, j0) = node
                size = 1 << (MAX_LEVEL - level)
                s0, s1 = i0 / (1 << MAX_LEVEL), (i0 + size) / (1 << MAX_LEVEL)
                t0, t1 = j0 / (1 << MAX_LEVEL), (j0 + size) / (1 << MAX_LEVEL)
                u0, u1 = float(_uv_from_st(s0)), float(_uv_from_st(s1))
                v0, v1 = float(_uv_from_st(t0)), float(_uv_from_st(t1))
                if u1 < region.u0 or u0 > region.u1 or v1 < region.v0 or v0 > region.v1:
                    continue  # disjoint
                contained = (
                    u0 >= region.u0 and u1 <= region.u1
                    and v0 >= region.v0 and v1 <= region.v1
                )
                stop = level >= self.max_level or (
                    contained
                    and level >= self.min_level
                    and (level - self.min_level) % self.level_mod == 0
                )
                if stop:
                    self._emit(face, level, pos, out)
                    emitted += 1
                else:
                    keep.append(node)
            if not keep:
                return
            if emitted + 4 * len(keep) > budget:
                for (level, pos, o, i0, j0) in keep:
                    self._emit(face, level, pos, out)
                return
            frontier = []
            for (level, pos, o, i0, j0) in keep:
                half = (1 << (MAX_LEVEL - level)) >> 1
                for p in range(4):
                    ij = int(POS_TO_IJ[o, p])
                    frontier.append(
                        (
                            level + 1,
                            (pos << 2) | p,
                            o ^ int(POS_TO_ORIENTATION[p]),
                            i0 + (ij >> 1) * half,
                            j0 + (ij & 1) * half,
                        )
                    )

    def _emit(self, face: int, level: int, pos: int, out: list) -> None:
        lsb = 1 << (2 * (MAX_LEVEL - level))
        cell = (face << _FACE_SHIFT) | ((pos << 1) * lsb) | lsb
        lo = cell - (lsb - 1)
        hi = cell + (lsb - 1)
        out.append((lo, hi))
