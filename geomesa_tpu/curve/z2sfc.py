"""Z2: 2-D space-filling curve over (lon, lat) points.

Functional parity with the reference's Z2SFC
(/root/reference/geomesa-z3/src/main/scala/org/locationtech/geomesa/curve/Z2SFC.scala):
31 bits per dimension over lon [-180,180] / lat [-90,90].
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from geomesa_tpu.curve.normalize import NormalizedLat, NormalizedLon
from geomesa_tpu.curve.zorder import Z2
from geomesa_tpu.curve.zranges import IndexRange, ZBox, zranges


class Z2SFC:
    def __init__(self, precision: int = 31):
        self.precision = precision
        self.lon = NormalizedLon(precision)
        self.lat = NormalizedLat(precision)

    def index(self, x, y) -> np.ndarray:
        """(lon, lat) -> z (vectorized). Reference Z2SFC.index."""
        return Z2.index(self.lon.normalize(x).astype(np.uint64), self.lat.normalize(y).astype(np.uint64))

    def normalize(self, x, y):
        """(lon, lat) -> (x_ord, y_ord) int32 dimension ordinals.

        TPU-first addition: the device table stores these decoded ordinals
        as int32 columns so the scan kernel never touches 64-bit z values.
        """
        return (
            self.lon.normalize(x).astype(np.int64),
            self.lat.normalize(y).astype(np.int64),
        )

    def invert(self, z):
        xi, yi = Z2.decode(z)
        return self.lon.denormalize(xi.astype(np.int64)), self.lat.denormalize(yi.astype(np.int64))

    def ranges(
        self,
        bounds: Sequence[tuple[float, float, float, float]],
        max_ranges: int | None = None,
        max_recurse: int | None = None,
        inner: bool = False,
    ) -> list[IndexRange]:
        """Covering z-ranges for (xmin, ymin, xmax, ymax) boxes.

        Boxes must be axis-ordered (min <= max per dimension); callers split
        antimeridian-crossing boxes into two, as the reference's do.
        ``inner=True``: classify containment 2 cells inward so contained
        rows are certain f64 hits (see Z3SFC.ranges).
        """
        boxes = []
        inner_boxes: list[ZBox] | None = [] if inner else None
        for (xmin, ymin, xmax, ymax) in bounds:
            if xmin > xmax or ymin > ymax:
                raise ValueError(f"inverted bbox: {(xmin, ymin, xmax, ymax)}")
            lo = (int(self.lon.normalize(xmin)), int(self.lat.normalize(ymin)))
            hi = (int(self.lon.normalize(xmax)), int(self.lat.normalize(ymax)))
            boxes.append(ZBox(lo, hi))
            if inner:
                inner_boxes.append(
                    ZBox(tuple(v + 2 for v in lo), tuple(max(v - 2, 0) for v in hi))
                )
        return zranges(
            Z2, boxes, max_ranges=max_ranges, max_recurse=max_recurse,
            inner_boxes=inner_boxes,
        )
