"""XZ2: 2-D XZ-ordering over (lon, lat) boxes — polygons/lines with extent.

Functional parity with the reference's XZ2SFC
(/root/reference/geomesa-z3/src/main/scala/org/locationtech/geomesa/curve/XZ2SFC.scala).
Default precision g=12 matches the reference's default XZ precision.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from geomesa_tpu.curve.xzsfc import XElement, XZSFC
from geomesa_tpu.curve.zranges import IndexRange

_INSTANCES: dict[int, "XZ2SFC"] = {}


class XZ2SFC:
    def __init__(self, g: int = 12):
        self.g = g
        self.core = XZSFC(g, dims=2)
        self.xmin, self.xmax = -180.0, 180.0
        self.ymin, self.ymax = -90.0, 90.0

    @staticmethod
    def for_precision(g: int = 12) -> "XZ2SFC":
        if g not in _INSTANCES:
            _INSTANCES[g] = XZ2SFC(g)
        return _INSTANCES[g]

    def _norm(self, x, lo, hi):
        return np.clip((np.asarray(x, dtype=np.float64) - lo) / (hi - lo), 0.0, 1.0)

    def index(self, xmin, ymin, xmax, ymax) -> np.ndarray:
        """Bounding boxes (vectorized) -> XZ2 codes. Reference XZ2SFC.index:54."""
        lo = np.stack(
            [self._norm(xmin, self.xmin, self.xmax), self._norm(ymin, self.ymin, self.ymax)],
            axis=-1,
        )
        hi = np.stack(
            [self._norm(xmax, self.xmin, self.xmax), self._norm(ymax, self.ymin, self.ymax)],
            axis=-1,
        )
        return self.core.index(np.atleast_2d(lo), np.atleast_2d(hi))

    def ranges(
        self,
        bounds: Sequence[tuple[float, float, float, float]],
        max_ranges: int | None = None,
    ) -> list[IndexRange]:
        queries = [
            XElement(
                (float(self._norm(b[0], self.xmin, self.xmax)), float(self._norm(b[1], self.ymin, self.ymax))),
                (float(self._norm(b[2], self.xmin, self.xmax)), float(self._norm(b[3], self.ymin, self.ymax))),
            )
            for b in bounds
        ]
        return self.core.ranges(queries, max_ranges=max_ranges)
