"""Space-filling-curve math: the pure-math foundation tier.

Equivalent of the reference's `geomesa-z3` module (see SURVEY.md section 2.1):
Morton (Z-order) bit interleaving in 2-D and 3-D, dimension normalization,
epoch-binned time, XZ-ordering for geometries with extent, and range
decomposition of query boxes into covering curve intervals.

Everything here is host-side vectorized NumPy (uint64): curve math runs at
plan/ingest time over batches of thousands, not in the per-row device hot
loop. The device scan path never touches 64-bit z values; it operates on the
decoded int32 dimension columns directly (see geomesa_tpu.scan).
"""

from geomesa_tpu.curve.zorder import Z2, Z3
from geomesa_tpu.curve.normalize import NormalizedDimension, NormalizedLat, NormalizedLon, NormalizedTime
from geomesa_tpu.curve.binnedtime import BinnedTime, TimePeriod
from geomesa_tpu.curve.z2sfc import Z2SFC
from geomesa_tpu.curve.z3sfc import Z3SFC
from geomesa_tpu.curve.xz2sfc import XZ2SFC
from geomesa_tpu.curve.xz3sfc import XZ3SFC

__all__ = [
    "Z2", "Z3", "NormalizedDimension", "NormalizedLat", "NormalizedLon", "NormalizedTime",
    "BinnedTime", "TimePeriod", "Z2SFC", "Z3SFC", "XZ2SFC", "XZ3SFC",
]
