"""Dimension normalization: double in [min,max] -> int in [0, 2^precision).

Functional parity with the reference's NormalizedDimension
(/root/reference/geomesa-z3/src/main/scala/org/locationtech/geomesa/curve/NormalizedDimension.scala:56-78):
floor-binning with clamp at the top, denormalize to bin centers, so that
``normalize(denormalize(i)) == i`` for all bins.

Vectorized over numpy arrays; also provides jnp variants usable inside jit
for on-device encoding (int32 — precisions here are <= 31 bits).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class NormalizedDimension:
    """Bit-normalized dimension (reference BitNormalizedDimension)."""

    min: float
    max: float
    precision: int  # bits

    def __post_init__(self):
        if not (0 < self.precision <= 31):
            raise ValueError(f"precision must be in (0, 31]: {self.precision}")

    @property
    def bins(self) -> int:
        return 1 << self.precision

    @property
    def max_index(self) -> int:
        return self.bins - 1

    @property
    def _normalizer(self) -> float:
        return self.bins / (self.max - self.min)

    @property
    def _denormalizer(self) -> float:
        return (self.max - self.min) / self.bins

    def normalize(self, d):
        """Map value(s) to bin ordinals, clamping to [0, max_index]."""
        d = np.asarray(d, dtype=np.float64)
        i = np.floor((d - self.min) * self._normalizer).astype(np.int64)
        return np.clip(i, 0, self.max_index)

    def denormalize(self, i):
        """Map bin ordinal(s) to the bin-center value."""
        i = np.asarray(i, dtype=np.float64)
        return self.min + (i + 0.5) * self._denormalizer

    # Inclusive value bounds of a bin -- used for exactness checks in range
    # decomposition (does a curve cell lie fully inside the query window?).
    def bin_min(self, i):
        i = np.asarray(i, dtype=np.float64)
        return self.min + i * self._denormalizer

    def bin_max(self, i):
        i = np.asarray(i, dtype=np.float64)
        return self.min + (i + 1.0) * self._denormalizer


def NormalizedLon(precision: int) -> NormalizedDimension:
    return NormalizedDimension(-180.0, 180.0, precision)


def NormalizedLat(precision: int) -> NormalizedDimension:
    return NormalizedDimension(-90.0, 90.0, precision)


def NormalizedTime(precision: int, max_offset: float) -> NormalizedDimension:
    """Time offset within a bin, [0, max_offset] (reference NormalizedTime)."""
    return NormalizedDimension(0.0, max_offset, precision)
