"""Z3: 3-D space-filling curve over (lon, lat, time-offset) points.

Functional parity with the reference's Z3SFC
(/root/reference/geomesa-z3/src/main/scala/org/locationtech/geomesa/curve/Z3SFC.scala:37-84):
21 bits per dimension; the time dimension spans the offset range of one
time bin (day/week/month/year — see geomesa_tpu.curve.binnedtime).
Per-period singleton instances mirror Z3SFC.apply.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from geomesa_tpu.curve.binnedtime import MAX_OFFSET, TimePeriod
from geomesa_tpu.curve.normalize import NormalizedLat, NormalizedLon, NormalizedTime
from geomesa_tpu.curve.zorder import Z3
from geomesa_tpu.curve.zranges import IndexRange, ZBox, zranges

_INSTANCES: dict[TimePeriod, "Z3SFC"] = {}


class Z3SFC:
    def __init__(self, period: "TimePeriod | str" = TimePeriod.WEEK, precision: int = 21):
        self.period = TimePeriod.parse(period)
        self.precision = precision
        self.lon = NormalizedLon(precision)
        self.lat = NormalizedLat(precision)
        self.time = NormalizedTime(precision, float(MAX_OFFSET[self.period]))

    @staticmethod
    def for_period(period: "TimePeriod | str") -> "Z3SFC":
        p = TimePeriod.parse(period)
        if p not in _INSTANCES:
            _INSTANCES[p] = Z3SFC(p)
        return _INSTANCES[p]

    def index(self, x, y, t) -> np.ndarray:
        """(lon, lat, offset) -> z (vectorized). Reference Z3SFC.index:37."""
        return Z3.index(
            self.lon.normalize(x).astype(np.uint64),
            self.lat.normalize(y).astype(np.uint64),
            self.time.normalize(t).astype(np.uint64),
        )

    def normalize(self, x, y, t):
        """(lon, lat, offset) -> int ordinals for the device columns."""
        return (
            self.lon.normalize(x).astype(np.int64),
            self.lat.normalize(y).astype(np.int64),
            self.time.normalize(t).astype(np.int64),
        )

    def invert(self, z):
        xi, yi, ti = Z3.decode(z)
        return (
            self.lon.denormalize(xi.astype(np.int64)),
            self.lat.denormalize(yi.astype(np.int64)),
            self.time.denormalize(ti.astype(np.int64)),
        )

    def ranges(
        self,
        bounds: Sequence[tuple[float, float, float, float]],
        times: Sequence[tuple[float, float]],
        max_ranges: int | None = None,
        max_recurse: int | None = None,
        inner: bool = False,
    ) -> list[IndexRange]:
        """Covering z-ranges for spatial boxes x time-offset windows.

        Reference Z3SFC.ranges:59-67 — the cartesian product of spatial
        bounds and (in-bin) time windows becomes one ZBox each.

        ``inner=True`` additionally classifies containment against ordinals
        shrunk 2 cells inward per dimension, making contained-range rows
        certain f64 hits (ScanConfig.contained_exact). The 2-cell margin
        absorbs normalize() floor rounding on both the query bounds and the
        stored values.
        """
        boxes = []
        inner_boxes: list[ZBox] | None = [] if inner else None
        for (xmin, ymin, xmax, ymax) in bounds:
            if xmin > xmax or ymin > ymax:
                raise ValueError(f"inverted bbox: {(xmin, ymin, xmax, ymax)}")
            for (tmin, tmax) in times:
                if tmin > tmax:
                    raise ValueError(f"inverted time window: {(tmin, tmax)}")
                lo = (
                    int(self.lon.normalize(xmin)),
                    int(self.lat.normalize(ymin)),
                    int(self.time.normalize(tmin)),
                )
                hi = (
                    int(self.lon.normalize(xmax)),
                    int(self.lat.normalize(ymax)),
                    int(self.time.normalize(tmax)),
                )
                boxes.append(ZBox(lo, hi))
                if inner:
                    inner_boxes.append(
                        ZBox(tuple(v + 2 for v in lo), tuple(max(v - 2, 0) for v in hi))
                    )
        return zranges(
            Z3, boxes, max_ranges=max_ranges, max_recurse=max_recurse,
            inner_boxes=inner_boxes,
        )
