"""Decomposition of query boxes into covering Z-curve ranges.

Functional parity with the reference's ZN.zranges
(/root/reference/geomesa-z3/src/main/scala/org/locationtech/geomesa/zorder/sfcurve/ZN.scala:110-242):
breadth-first quad/oct-tree traversal from the longest common prefix of the
query corners, emitting:

- *contained* ranges: curve cells fully inside every queried dimension
  interval (rows in them need no further spatial/temporal filtering), and
- *overlapping* ranges: cells that straddle the query boundary (rows need
  the per-row membership test — on TPU, the scan kernel mask).

The traversal is budgeted: `max_ranges` caps output size (reference default
``geomesa.scan.ranges.target`` = 2000, QueryProperties.scala) and
`max_recurse` caps depth (ZN.DefaultRecurse = 7 levels past the common
prefix). When the budget is hit, remaining cells are emitted as coarse
overlapping ranges — always a superset of the query, never a miss.

Host-side pure Python/NumPy: this runs once per query over thousands of
cells, not per row. Keeping range count bounded keeps the device scan grid
static-shaped for XLA (SURVEY.md hard part (d)).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import numpy as np

from geomesa_tpu.curve.zorder import _ZN, longest_common_prefix, zdiv  # noqa: F401

DEFAULT_MAX_RECURSE = 7


@dataclass(frozen=True)
class IndexRange:
    """Inclusive z-range [lower, upper]; contained = no row filter needed."""

    lower: int
    upper: int
    contained: bool


@dataclass(frozen=True)
class ZBox:
    """A query box in z-space: per-dimension normalized [min, max] ordinals."""

    mins: tuple[int, ...]
    maxes: tuple[int, ...]


def zranges(
    curve,
    boxes: Sequence[ZBox],
    max_ranges: int | None = None,
    max_recurse: int | None = None,
    inner_boxes: "Sequence[ZBox] | None" = None,
) -> list[IndexRange]:
    """Covering z-ranges for the union of ``boxes`` on ``curve``.

    curve: Z2 or Z3 from geomesa_tpu.curve.zorder (needs .dims,
    .bits_per_dim, .index, .decode).

    ``inner_boxes`` (aligned with ``boxes``) classify *containment*: a cell
    is contained only when fully inside some inner box. Callers pass boxes
    shrunk below the f64 query bounds so contained-range rows are certain
    hits needing no refinement; default (None) classifies against the outer
    boxes — ordinal-level containment, the reference ZN.zranges behavior.
    Inner boxes may be inverted (mins > maxes) to mean "never contained".
    """
    if not boxes:
        return []
    if max_ranges is None:
        from geomesa_tpu.conf import SCAN_RANGES_TARGET

        max_ranges = SCAN_RANGES_TARGET.get()
    if max_ranges < 1:
        raise ValueError(f"max_ranges must be >= 1: {max_ranges}")
    max_recurse = DEFAULT_MAX_RECURSE if max_recurse is None else max_recurse
    dims = curve.dims
    bits_per_dim = curve.bits_per_dim
    total_bits = dims * bits_per_dim
    children = 1 << dims

    for b in boxes:
        for d in range(dims):
            if b.mins[d] > b.maxes[d]:
                raise ValueError(f"inverted box on dim {d}: {b.mins} > {b.maxes}")

    mins = np.array([b.mins for b in boxes], dtype=np.uint64)  # [nbox, dims]
    maxes = np.array([b.maxes for b in boxes], dtype=np.uint64)
    if inner_boxes is None:
        imins, imaxes = mins, maxes
    else:
        # inverted inner dims (mins > maxes) never contain anything
        imins = np.array([b.mins for b in inner_boxes], dtype=np.uint64)
        imaxes = np.array([b.maxes for b in inner_boxes], dtype=np.uint64)

    from geomesa_tpu import native

    nat = native.zranges(
        dims, bits_per_dim, mins, maxes, imins, imaxes, max_ranges, max_recurse
    )
    if nat is not None:
        lo, hi, cont = nat
        return [
            IndexRange(int(l), int(h), bool(c))
            for l, h, c in zip(lo.tolist(), hi.tolist(), cont.tolist())
        ]

    zmins = [int(curve.index(*b.mins)) for b in boxes]
    zmaxes = [int(curve.index(*b.maxes)) for b in boxes]

    # longest common prefix over all corner z-values, aligned to dims bits
    lcp = longest_common_prefix(curve, *(zmins + zmaxes))
    offset = lcp.offset
    prefix = lcp.prefix

    ranges: list[IndexRange] = []

    def cell_bounds(z_prefix: int, level_bits: int) -> tuple[np.ndarray, np.ndarray]:
        """Per-dimension [lo, hi] ordinals of the cell with the given prefix;
        level_bits = number of low bits free within the cell."""
        zmin = z_prefix
        zmax = z_prefix | ((1 << level_bits) - 1)
        lo = np.array(curve.decode(np.uint64(zmin)), dtype=np.uint64)
        hi = np.array(curve.decode(np.uint64(zmax)), dtype=np.uint64)
        return lo, hi

    def classify(lo: np.ndarray, hi: np.ndarray) -> int:
        """2 = fully contained in some inner box, 1 = overlaps some box,
        0 = disjoint."""
        contained = np.all((lo >= imins) & (hi <= imaxes), axis=1)
        if contained.any():
            return 2
        overlaps = np.all((lo <= maxes) & (hi >= mins), axis=1)
        if overlaps.any():
            return 1
        return 0

    # BFS over cells. Each entry: (z_prefix, free_bits)
    level = [(prefix, offset)]
    recursions = 0
    while level and recursions < max_recurse and len(ranges) + len(level) * children < max_ranges * 2:
        nxt: list[tuple[int, int]] = []
        for z_prefix, free_bits in level:
            if free_bits == 0:
                lo, hi = cell_bounds(z_prefix, 0)
                c = classify(lo, hi)
                if c:
                    ranges.append(IndexRange(z_prefix, z_prefix, c == 2))
                continue
            child_bits = free_bits - dims
            for q in range(children):
                child_prefix = z_prefix | (q << child_bits)
                lo, hi = cell_bounds(child_prefix, child_bits)
                c = classify(lo, hi)
                if c == 2:
                    ranges.append(
                        IndexRange(child_prefix, child_prefix | ((1 << child_bits) - 1), True)
                    )
                elif c == 1:
                    if child_bits == 0:
                        ranges.append(IndexRange(child_prefix, child_prefix, False))
                    else:
                        nxt.append((child_prefix, child_bits))
        level = nxt
        recursions += 1

    # budget exhausted: emit remaining cells as coarse overlapping ranges
    for z_prefix, free_bits in level:
        ranges.append(IndexRange(z_prefix, z_prefix | ((1 << free_bits) - 1), False))

    merged = merge_ranges(ranges, max_ranges)
    return _tighten_ranges(curve, merged, zmins, zmaxes, mins, maxes)


def _tighten_ranges(
    curve,
    ranges: list[IndexRange],
    zmins: list[int],
    zmaxes: list[int],
    mins: np.ndarray,
    maxes: np.ndarray,
) -> list[IndexRange]:
    """Shrink range endpoints to in-union z-values via LITMAX/BIGMIN.

    The reference invokes zdiv from its range decomposition to skip the gap
    at a miss (ZN.scala:309-361 called from the zranges loop); here the BFS
    classifies whole cells, so the equivalent tightening runs as a post-pass
    against the union of query boxes: each range's lower endpoint advances to
    the smallest z >= it inside *some* box (min of per-box BIGMINs), the
    upper retracts to the largest z <= it inside some box (max of per-box
    LITMAXs), and ranges containing no in-union z are dropped. In Morton
    order the z of a box's min/max corner is that box's global min/max z,
    which bounds the per-box candidate search.
    """

    def in_box(z: int, b: int) -> bool:
        pt = np.array(curve.decode(np.uint64(z)), dtype=np.uint64)
        return bool(np.all(pt >= mins[b]) & np.all(pt <= maxes[b]))

    nbox = len(zmins)
    out: list[IndexRange] = []
    for r in ranges:
        lo_cands: list[int] = []
        hi_cands: list[int] = []
        for b in range(nbox):
            zmin, zmax = zmins[b], zmaxes[b]
            if zmax < r.lower or zmin > r.upper:
                continue  # box b has no z in this range's window at all
            # smallest z of box b that is >= r.lower
            if r.lower <= zmin:
                cand = zmin
            elif in_box(r.lower, b):
                cand = r.lower
            else:
                _, cand = zdiv(curve, zmin, zmax, r.lower)
            if cand <= r.upper:
                lo_cands.append(cand)
            # largest z of box b that is <= r.upper
            if r.upper >= zmax:
                cand = zmax
            elif in_box(r.upper, b):
                cand = r.upper
            else:
                cand, _ = zdiv(curve, zmin, zmax, r.upper)
            if cand >= r.lower:
                hi_cands.append(cand)
        if not lo_cands or not hi_cands:
            continue
        lo, hi = min(lo_cands), max(hi_cands)
        if lo > hi:
            continue
        out.append(IndexRange(lo, hi, r.contained))
    return out


def merge_ranges(ranges: list[IndexRange], max_ranges: int | None = None) -> list[IndexRange]:
    """Sort, merge overlapping/adjacent ranges, and reduce below max_ranges
    by closing the smallest gaps first (over-covering, never dropping).

    Reference: the sort+merge at the tail of ZN.zranges (ZN.scala:198-242).
    """
    if not ranges:
        return []
    ranges = sorted(ranges, key=lambda r: (r.lower, r.upper))
    merged: list[IndexRange] = [ranges[0]]
    for r in ranges[1:]:
        last = merged[-1]
        # merge only same-kind neighbors: a contained range keeps its
        # no-refinement guarantee instead of degrading when glued to an
        # overlapping one (BFS cells are disjoint, so ranges only touch)
        if r.lower <= last.upper + 1 and r.contained == last.contained:
            merged[-1] = IndexRange(last.lower, max(last.upper, r.upper), last.contained)
        else:
            merged.append(r)
    if max_ranges is not None and len(merged) > max_ranges:
        # close smallest gaps until under budget
        gaps = np.array(
            [merged[i + 1].lower - merged[i].upper for i in range(len(merged) - 1)]
        )
        k = len(merged) - max_ranges
        cutoff_idx = np.argpartition(gaps, k - 1)[:k]
        close = np.zeros(len(gaps), dtype=bool)
        close[cutoff_idx] = True
        out: list[IndexRange] = [merged[0]]
        for i, r in enumerate(merged[1:]):
            if close[i]:
                last = out[-1]
                out[-1] = IndexRange(last.lower, max(last.upper, r.upper), False)
            else:
                out.append(r)
        merged = out
    return merged


def ranges_to_arrays(ranges: list[IndexRange]):
    """(lower u64[n], upper u64[n], contained bool[n]) arrays for searchsorted."""
    lo = np.array([r.lower for r in ranges], dtype=np.uint64)
    hi = np.array([r.upper for r in ranges], dtype=np.uint64)
    contained = np.array([r.contained for r in ranges], dtype=bool)
    return lo, hi, contained
