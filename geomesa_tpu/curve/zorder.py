"""Morton (Z-order) bit interleaving, vectorized over numpy uint64.

Functional parity with the reference's sfcurve Z2/Z3 objects
(/root/reference/geomesa-z3/src/main/scala/org/locationtech/geomesa/zorder/sfcurve/Z2.scala,
 Z3.scala:54-91): 2-D interleave at 31 bits/dim (62-bit keys) and 3-D
interleave at 21 bits/dim (63-bit keys), via parallel-prefix magic-mask
split/combine.

All functions accept scalars or numpy arrays and are fully vectorized —
this is the TPU-first restatement of the reference's scalar per-row loop:
ingest encodes whole column batches at once.

Also implements the Tropf/Herzog LITMAX/BIGMIN split (`zdiv`, reference
ZN.scala:309-361) used to tighten range decomposition, and the quadrant
BFS decomposition (`zranges`, reference ZN.scala:110-242) in
geomesa_tpu.curve.zranges.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

_U = np.uint64


def _u(x) -> np.uint64:
    return np.asarray(x, dtype=np.uint64)


class _ZN:
    """Shared shape of an N-dimensional Morton curve (reference ZN.scala)."""

    dims: int
    bits_per_dim: int

    @property
    def total_bits(self) -> int:
        return self.dims * self.bits_per_dim

    @property
    def max_mask(self) -> int:
        return (1 << self.bits_per_dim) - 1

    # -- to be provided by subclasses ------------------------------------
    def split(self, x):  # pragma: no cover - interface
        raise NotImplementedError

    def combine(self, z):  # pragma: no cover - interface
        raise NotImplementedError

class _Z2(_ZN):
    """2-D Morton: 31 bits per dimension, 62-bit keys (reference Z2.scala)."""

    dims = 2
    bits_per_dim = 31

    def split(self, value):
        """Insert a 0 bit between each of the low 31 bits of ``value``."""
        x = _u(value) & _U(0x7FFFFFFF)
        x = (x ^ (x << _U(32))) & _U(0x00000000FFFFFFFF)
        x = (x ^ (x << _U(16))) & _U(0x0000FFFF0000FFFF)
        x = (x ^ (x << _U(8))) & _U(0x00FF00FF00FF00FF)
        x = (x ^ (x << _U(4))) & _U(0x0F0F0F0F0F0F0F0F)
        x = (x ^ (x << _U(2))) & _U(0x3333333333333333)
        x = (x ^ (x << _U(1))) & _U(0x5555555555555555)
        return x

    def combine(self, z):
        """Inverse of split: extract every second bit."""
        x = _u(z) & _U(0x5555555555555555)
        x = (x ^ (x >> _U(1))) & _U(0x3333333333333333)
        x = (x ^ (x >> _U(2))) & _U(0x0F0F0F0F0F0F0F0F)
        x = (x ^ (x >> _U(4))) & _U(0x00FF00FF00FF00FF)
        x = (x ^ (x >> _U(8))) & _U(0x0000FFFF0000FFFF)
        x = (x ^ (x >> _U(16))) & _U(0x00000000FFFFFFFF)
        return x

    def index(self, x, y):
        """Interleave: z = split(x) | split(y) << 1."""
        return self.split(x) | (self.split(y) << _U(1))

    def decode(self, z):
        z = _u(z)
        return self.combine(z), self.combine(z >> _U(1))


class _Z3(_ZN):
    """3-D Morton: 21 bits per dimension, 63-bit keys (reference Z3.scala)."""

    dims = 3
    bits_per_dim = 21

    def split(self, value):
        """Spread the low 21 bits of ``value`` to every third bit."""
        x = _u(value) & _U(0x1FFFFF)
        x = (x | (x << _U(32))) & _U(0x1F00000000FFFF)
        x = (x | (x << _U(16))) & _U(0x1F0000FF0000FF)
        x = (x | (x << _U(8))) & _U(0x100F00F00F00F00F)
        x = (x | (x << _U(4))) & _U(0x10C30C30C30C30C3)
        x = (x | (x << _U(2))) & _U(0x1249249249249249)
        return x

    def combine(self, z):
        """Inverse of split: extract every third bit."""
        x = _u(z) & _U(0x1249249249249249)
        x = (x ^ (x >> _U(2))) & _U(0x10C30C30C30C30C3)
        x = (x ^ (x >> _U(4))) & _U(0x100F00F00F00F00F)
        x = (x ^ (x >> _U(8))) & _U(0x1F0000FF0000FF)
        x = (x ^ (x >> _U(16))) & _U(0x1F00000000FFFF)
        x = (x ^ (x >> _U(32))) & _U(0x1FFFFF)
        return x

    def index(self, x, y, t):
        """Interleave: z = split(x) | split(y) << 1 | split(t) << 2."""
        return self.split(x) | (self.split(y) << _U(1)) | (self.split(t) << _U(2))

    def decode(self, z):
        z = _u(z)
        return self.combine(z), self.combine(z >> _U(1)), self.combine(z >> _U(2))


Z2 = _Z2()
Z3 = _Z3()


@dataclass(frozen=True)
class ZPrefix:
    """Longest common binary prefix of two z-values (reference ZN.scala:250-265)."""

    prefix: int
    offset: int  # number of (low) bits NOT in the prefix


def longest_common_prefix(curve: _ZN, *values: int) -> ZPrefix:
    """Longest common prefix, in increments of ``dims`` bits.

    Reference: ZN.longestCommonPrefix (ZN.scala:250-265). Quad/oct tree
    levels consume `dims` bits at a time, so the prefix is aligned to the
    dimension count. Scans from the top for the smallest aligned offset at
    which all values share the same high bits.
    """
    step = curve.dims
    first = values[0]
    offset = curve.total_bits
    while offset > 0:
        nxt = offset - step
        bits = first >> nxt
        if all((v >> nxt) == bits for v in values):
            offset = nxt
        else:
            break
    return ZPrefix(prefix=(first >> offset) << offset, offset=offset)


def zdiv(curve: _ZN, zmin: int, zmax: int, zval: int) -> tuple[int, int]:
    """Tropf/Herzog LITMAX/BIGMIN computation.

    Given a z-range [zmin, zmax] (whose decoded corners span a query box)
    and a value ``zval`` inside [zmin, zmax] but *outside* the box, return
    (litmax, bigmin): litmax = the largest z <= zval inside the box,
    bigmin = the smallest z >= zval inside the box. Used to split a search
    range at a miss, skipping the gap.

    Reference: ZN.zdiv (ZN.scala:309-361). This implementation walks bits
    from the top, maintaining per-call load/bits semantics equivalent to the
    published algorithm (Tropf & Herzog 1981), generalized to N dims.
    """
    dims = curve.dims
    total = curve.total_bits
    litmax = zmin
    bigmin = zmax

    zmin_, zmax_ = zmin, zmax

    def load(target: int, p: int, bits: int, dim: int) -> int:
        """Set the bits of dimension `dim` in `target` at/below position
        `bits` (dimension-local bit count) to the pattern `p`.

        The dimension-strided mask/pattern are the curve's own split()
        spread shifted to the dimension lane — no per-bit loops.
        """
        mask = int(curve.split(np.uint64((1 << bits) - 1))) << dim
        pattern = int(curve.split(np.uint64(p & ((1 << bits) - 1)))) << dim
        return (target & ~mask) | pattern

    for i in range(total - 1, -1, -1):
        bit = 1 << i
        dim = i % dims
        bits_local = i // dims + 1  # dim-local index of this bit, 1-based
        v_bit = 1 if (zval & bit) else 0
        min_bit = 1 if (zmin_ & bit) else 0
        max_bit = 1 if (zmax_ & bit) else 0
        if v_bit == 0 and min_bit == 0 and max_bit == 0:
            continue
        if v_bit == 0 and min_bit == 0 and max_bit == 1:
            bigmin = load(zmin_, 1 << (bits_local - 1), bits_local, dim)
            zmax_ = load(zmax_, (1 << (bits_local - 1)) - 1, bits_local, dim)
        elif v_bit == 0 and min_bit == 1 and max_bit == 1:
            bigmin = zmin_
            return litmax, bigmin
        elif v_bit == 1 and min_bit == 0 and max_bit == 0:
            litmax = zmax_
            return litmax, bigmin
        elif v_bit == 1 and min_bit == 0 and max_bit == 1:
            litmax = load(zmax_, (1 << (bits_local - 1)) - 1, bits_local, dim)
            zmin_ = load(zmin_, 1 << (bits_local - 1), bits_local, dim)
        elif v_bit == 1 and min_bit == 1 and max_bit == 1:
            continue
        else:  # (0,1,0) and (1,1,0) are impossible for zmin <= zmax on this path
            raise ValueError(f"inconsistent bits at {i}: {v_bit} {min_bit} {max_bit}")
    return litmax, bigmin
