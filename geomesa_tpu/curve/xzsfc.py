"""XZ-ordering (Boehm, Klump & Kriegel) for geometries *with extent*.

Generic N-dimensional core shared by XZ2 (2-D, polygons/lines) and XZ3
(3-D, extents + time). Functional parity with the reference's XZ2SFC
(/root/reference/geomesa-z3/src/main/scala/org/locationtech/geomesa/curve/XZ2SFC.scala:54-306)
and XZ3SFC (XZ3SFC.scala), re-derived from the published XZ-ordering
construction rather than translated:

- An element (an N-d box) is assigned the deepest tree level ``l`` at which
  it still fits inside an *enlarged* cell (a cell doubled in every
  dimension, anchored at the cell's low corner); its code is the preorder
  sequence number of the cell containing its low corner at level ``l``.
- A query box's covering ranges come from a BFS over the 2^N-ary tree:
  cells whose enlarged extent is contained in the query cover their whole
  subtree (*contained* ranges, no row filter needed); cells whose enlarged
  extent merely overlaps contribute their own code (*overlapping*) and
  recurse.

Sequence codes fit in int64 for the default precision g=12
(2-D: (4^13-1)/3 ~ 2.2e7; 3-D: (8^13-1)/7 ~ 7.8e10).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import numpy as np

from geomesa_tpu.curve.zranges import IndexRange, merge_ranges



@dataclass(frozen=True)
class XElement:
    """A normalized query/element box: per-dim [lo, hi] in [0, 1]."""

    lo: tuple[float, ...]
    hi: tuple[float, ...]


class XZSFC:
    """N-dimensional XZ curve with ``g`` levels of resolution."""

    def __init__(self, g: int, dims: int):
        if dims * (g + 1) > 62:
            # preorder codes bounded by (2^dims)^(g+1)/(2^dims - 1)
            raise ValueError(f"g={g} too deep for {dims}-d int64 sequence codes")
        self.g = g
        self.dims = dims
        self.children = 1 << dims
        # subtree_size[l] = number of nodes in a subtree rooted at level l
        # (levels l..g): sum_{i=0..g-l} children^i
        sizes = []
        for l in range(g + 2):
            depth = g - l
            if depth < 0:
                sizes.append(0)
            else:
                sizes.append((self.children ** (depth + 1) - 1) // (self.children - 1))
        self.subtree_size = sizes  # index by level of the subtree root

    # -- write path ------------------------------------------------------

    def length_at(self, lo: np.ndarray, hi: np.ndarray) -> np.ndarray:
        """Deepest level at which each element fits in an enlarged cell.

        Vectorized over elements: lo/hi are [n, dims] normalized to [0,1].
        Reference: the resolution computation in XZ2SFC.index:63-73.
        """
        extent = np.max(hi - lo, axis=1)
        with np.errstate(divide="ignore"):
            l1 = np.floor(np.log(np.maximum(extent, 1e-300)) / np.log(0.5)).astype(np.int64)
        l1 = np.minimum(l1, self.g)
        # can we go one level deeper? the enlarged cell at l1+1 anchored at
        # the element's low corner's cell must still contain the element.
        w2 = np.power(0.5, np.minimum(l1 + 1, self.g))  # cell width at l1+1
        fits = np.ones(len(l1), dtype=bool)
        for d in range(self.dims):
            anchor = np.floor(lo[:, d] / w2) * w2
            fits &= hi[:, d] <= anchor + 2 * w2
        length = np.where(fits, np.minimum(l1 + 1, self.g), np.maximum(l1, 0))
        return np.clip(length, 0, self.g)

    def sequence_code(self, point: np.ndarray, length: np.ndarray) -> np.ndarray:
        """Preorder code of the level-``length`` cell containing ``point``.

        Vectorized: point is [n, dims] in [0,1], length is [n].
        Reference: XZ2SFC.sequenceCode:264-286.
        """
        n = len(point)
        cs = np.zeros(n, dtype=np.int64)
        lo = np.zeros((n, self.dims))
        hi = np.ones((n, self.dims))
        for i in range(self.g):
            active = i < length
            if not active.any():
                break
            center = (lo + hi) * 0.5
            ge = point >= center  # [n, dims] bools
            q = np.zeros(n, dtype=np.int64)
            for d in range(self.dims):
                q |= ge[:, d].astype(np.int64) << d
            subtree = self.subtree_size[i + 1]
            cs = np.where(active, cs + 1 + q * subtree, cs)
            lo_new = np.where(ge, center, lo)
            hi_new = np.where(ge, hi, center)
            lo = np.where(active[:, None], lo_new, lo)
            hi = np.where(active[:, None], hi_new, hi)
        return cs

    def index(self, lo: np.ndarray, hi: np.ndarray) -> np.ndarray:
        """Element boxes [n, dims] -> XZ codes [n]. Reference XZ2SFC.index:54.

        Native C++ scalar pass when available (the extent ingest hot loop;
        ~2*g numpy full-array passes otherwise), exact numpy fallback —
        parity asserted in tests/test_native.py."""
        lo = np.atleast_2d(np.asarray(lo, dtype=np.float64))
        hi = np.atleast_2d(np.asarray(hi, dtype=np.float64))

        from geomesa_tpu import native

        out = native.xz_index(lo, hi, self.dims, self.g, self.subtree_size)
        if out is not None:
            return out
        length = self.length_at(lo, hi)
        return self.sequence_code(lo, length)

    # -- read path -------------------------------------------------------

    def ranges(
        self,
        queries: Sequence[XElement],
        max_ranges: int | None = None,
    ) -> list[IndexRange]:
        """Covering code ranges for the union of normalized query boxes.

        Reference: XZ2SFC.ranges:146-252.
        """
        if not queries:
            return []
        if max_ranges is None:
            from geomesa_tpu.conf import SCAN_RANGES_TARGET

            max_ranges = SCAN_RANGES_TARGET.get()
        if max_ranges < 1:
            raise ValueError(f"max_ranges must be >= 1: {max_ranges}")
        qlo = np.array([q.lo for q in queries])  # [nq, dims]
        qhi = np.array([q.hi for q in queries])

        from geomesa_tpu import native

        nat = native.xz_ranges(
            self.dims, self.g, self.subtree_size, qlo, qhi, max_ranges
        )
        if nat is not None:
            lo, hi, cont = nat
            return [
                IndexRange(int(a), int(b), bool(c))
                for a, b, c in zip(lo.tolist(), hi.tolist(), cont.tolist())
            ]

        ranges: list[IndexRange] = []
        # queue entries: (cell lo tuple, level, cs)
        level_cells: list[tuple[tuple[float, ...], int, int]] = [((0.0,) * self.dims, 0, 0)]
        # process the root explicitly: its enlarged cell is the whole space
        while level_cells:
            nxt: list[tuple[tuple[float, ...], int, int]] = []
            budget_left = max_ranges * 2 - len(ranges)
            if budget_left <= 0:
                break
            for (clo, level, cs) in level_cells:
                w = 0.5**level
                cell_lo = np.array(clo)
                enl_hi = cell_lo + 2 * w  # enlarged cell
                contained = np.any(
                    np.all((qlo <= cell_lo) & (qhi >= enl_hi), axis=1)
                )
                if contained:
                    ranges.append(
                        IndexRange(cs, cs + self.subtree_size[level] - 1, True)
                    )
                    continue
                overlaps = np.any(
                    np.all((qlo <= enl_hi) & (qhi >= cell_lo), axis=1)
                )
                if not overlaps:
                    continue
                ranges.append(IndexRange(cs, cs, False))
                if level < self.g:
                    subtree = self.subtree_size[level + 1]
                    half = w * 0.5
                    for q in range(self.children):
                        child_lo = tuple(
                            clo[d] + (half if (q >> d) & 1 else 0.0)
                            for d in range(self.dims)
                        )
                        nxt.append((child_lo, level + 1, cs + 1 + q * subtree))
            level_cells = nxt

        # budget exhausted: emit whole subtrees for unprocessed cells
        for (clo, level, cs) in level_cells:
            cell_lo = np.array(clo)
            w = 0.5**level
            enl_hi = cell_lo + 2 * w
            if np.any(np.all((qlo <= enl_hi) & (qhi >= cell_lo), axis=1)):
                ranges.append(IndexRange(cs, cs + self.subtree_size[level] - 1, False))

        return merge_ranges(ranges, max_ranges)
