"""XZ3: 3-D XZ-ordering over (lon, lat, time-offset) boxes.

Functional parity with the reference's XZ3SFC
(/root/reference/geomesa-z3/src/main/scala/org/locationtech/geomesa/curve/XZ3SFC.scala):
geometries with extent plus a time dimension, per time bin (the bin is a
separate key prefix, as in Z3). Default precision g=12.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from geomesa_tpu.curve.binnedtime import MAX_OFFSET, TimePeriod
from geomesa_tpu.curve.xzsfc import XElement, XZSFC
from geomesa_tpu.curve.zranges import IndexRange

_INSTANCES: dict[tuple[int, TimePeriod], "XZ3SFC"] = {}


class XZ3SFC:
    def __init__(self, period: "TimePeriod | str" = TimePeriod.WEEK, g: int = 12):
        self.period = TimePeriod.parse(period)
        self.g = g
        self.core = XZSFC(g, dims=3)
        self.xmin, self.xmax = -180.0, 180.0
        self.ymin, self.ymax = -90.0, 90.0
        self.tmin, self.tmax = 0.0, float(MAX_OFFSET[self.period])

    @staticmethod
    def for_period(period: "TimePeriod | str", g: int = 12) -> "XZ3SFC":
        p = TimePeriod.parse(period)
        key = (g, p)
        if key not in _INSTANCES:
            _INSTANCES[key] = XZ3SFC(p, g)
        return _INSTANCES[key]

    def _norm(self, x, lo, hi):
        return np.clip((np.asarray(x, dtype=np.float64) - lo) / (hi - lo), 0.0, 1.0)

    def index(self, xmin, ymin, tmin, xmax, ymax, tmax) -> np.ndarray:
        lo = np.stack(
            [
                self._norm(xmin, self.xmin, self.xmax),
                self._norm(ymin, self.ymin, self.ymax),
                self._norm(tmin, self.tmin, self.tmax),
            ],
            axis=-1,
        )
        hi = np.stack(
            [
                self._norm(xmax, self.xmin, self.xmax),
                self._norm(ymax, self.ymin, self.ymax),
                self._norm(tmax, self.tmin, self.tmax),
            ],
            axis=-1,
        )
        return self.core.index(np.atleast_2d(lo), np.atleast_2d(hi))

    def ranges(
        self,
        bounds: Sequence[tuple[float, float, float, float, float, float]],
        max_ranges: int | None = None,
    ) -> list[IndexRange]:
        """bounds: (xmin, ymin, tmin, xmax, ymax, tmax) tuples."""
        queries = [
            XElement(
                (
                    float(self._norm(b[0], self.xmin, self.xmax)),
                    float(self._norm(b[1], self.ymin, self.ymax)),
                    float(self._norm(b[2], self.tmin, self.tmax)),
                ),
                (
                    float(self._norm(b[3], self.xmin, self.xmax)),
                    float(self._norm(b[4], self.ymin, self.ymax)),
                    float(self._norm(b[5], self.tmin, self.tmax)),
                ),
            )
            for b in bounds
        ]
        return self.core.ranges(queries, max_ranges=max_ranges)
