"""Epoch-binned time: timestamp -> (short bin, long offset-into-bin).

Functional parity with the reference's BinnedTime
(/root/reference/geomesa-z3/src/main/scala/org/locationtech/geomesa/curve/BinnedTime.scala:16-65):

- period Day   -> bin = days since 1970-01-01,   offset in MILLIS
- period Week  -> bin = weeks since 1970-01-01,  offset in SECONDS
- period Month -> bin = calendar months since 1970-01, offset in SECONDS
- period Year  -> bin = calendar years since 1970, offset in MINUTES

Bins are int16 ("short" in the reference); offsets fit in the Z3/XZ3 time
dimension (21 bits covers a week of seconds: 604800 < 2^21).

All conversions are vectorized over numpy int64 arrays of epoch
milliseconds. Month/Year use numpy datetime64 calendar arithmetic, which
matches java.time ChronoUnit month/year bin boundaries.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

import numpy as np

MILLIS_PER_DAY = 86_400_000
SECONDS_PER_WEEK = 604_800


class TimePeriod(enum.Enum):
    DAY = "day"
    WEEK = "week"
    MONTH = "month"
    YEAR = "year"

    @staticmethod
    def parse(s: "str | TimePeriod") -> "TimePeriod":
        if isinstance(s, TimePeriod):
            return s
        return TimePeriod(s.lower())


# Max offset value within a bin, per period (reference BinnedTime.maxOffset):
# day -> millis/day, week -> seconds/week, month -> seconds in a 31-day month,
# year -> minutes in a 366-day year.
MAX_OFFSET = {
    TimePeriod.DAY: MILLIS_PER_DAY - 1,
    TimePeriod.WEEK: SECONDS_PER_WEEK - 1,
    TimePeriod.MONTH: 31 * 24 * 60 * 60 - 1,
    TimePeriod.YEAR: 366 * 24 * 60 - 1,
}

# Largest representable date per period: bins are int16, so the max bin is
# 2^15 - 1 (reference BinnedTime.maxDate). We only need the bin arithmetic.
MAX_BIN = (1 << 15) - 1


@dataclass(frozen=True)
class BinnedValue:
    bin: np.ndarray  # int16-valued (held as int32 for safe arithmetic)
    offset: np.ndarray  # int64


class BinnedTime:
    """Vectorized epoch-millis <-> (bin, offset) codec for one period."""

    def __init__(self, period: "TimePeriod | str"):
        self.period = TimePeriod.parse(period)

    @property
    def max_offset(self) -> int:
        return MAX_OFFSET[self.period]

    def to_binned(self, millis) -> BinnedValue:
        """Epoch millis -> (bin, offset). Reference: timeToBinnedTime (:73).

        Out-of-range instants (pre-epoch, or past the max representable bin)
        raise, mirroring the reference's require checks
        (BinnedTime.scala:202-204) — silent clamping would alias distinct
        instants onto boundary bins and corrupt query results.
        """
        ms = np.asarray(millis, dtype=np.int64)
        if np.any(ms < 0):
            raise ValueError(
                f"pre-epoch timestamp(s) not supported by period {self.period.value}: "
                f"min={int(np.min(ms))}ms"
            )
        p = self.period
        if p is TimePeriod.DAY:
            b = np.floor_divide(ms, MILLIS_PER_DAY)
            off = ms - b * MILLIS_PER_DAY
        elif p is TimePeriod.WEEK:
            b = np.floor_divide(ms, MILLIS_PER_DAY * 7)
            off = np.floor_divide(ms - b * (MILLIS_PER_DAY * 7), 1000)
        elif p is TimePeriod.MONTH:
            dt = ms.astype("datetime64[ms]")
            months = dt.astype("datetime64[M]")
            b = months.astype(np.int64)
            off = np.floor_divide((dt - months).astype("timedelta64[ms]").astype(np.int64), 1000)
        else:  # YEAR
            dt = ms.astype("datetime64[ms]")
            years = dt.astype("datetime64[Y]")
            b = years.astype(np.int64)
            off = np.floor_divide((dt - years).astype("timedelta64[ms]").astype(np.int64), 60_000)
        if np.any(b > MAX_BIN):
            raise ValueError(
                f"timestamp(s) past the max representable date for period "
                f"{self.period.value} (bin {int(np.max(b))} > {MAX_BIN})"
            )
        return BinnedValue(bin=b.astype(np.int32), offset=off.astype(np.int64))

    def from_binned(self, bin, offset) -> np.ndarray:
        """(bin, offset) -> epoch millis (start-of-offset instant)."""
        b = np.asarray(bin, dtype=np.int64)
        off = np.asarray(offset, dtype=np.int64)
        p = self.period
        if p is TimePeriod.DAY:
            return b * MILLIS_PER_DAY + off
        if p is TimePeriod.WEEK:
            return b * (MILLIS_PER_DAY * 7) + off * 1000
        if p is TimePeriod.MONTH:
            base = b.astype("datetime64[M]").astype("datetime64[ms]").astype(np.int64)
            return base + off * 1000
        base = b.astype("datetime64[Y]").astype("datetime64[ms]").astype(np.int64)
        return base + off * 60_000

    def bin_start_millis(self, bin) -> np.ndarray:
        return self.from_binned(bin, 0)

    def bins_for_interval(self, lo_millis: int, hi_millis: int):
        """All (bin, lo_offset, hi_offset) triples covering [lo, hi] millis.

        The analogue of the reference's BinnedTime.timesByBin logic used by
        Z3IndexKeySpace (Z3IndexKeySpace.scala:132-158): a long interval is
        tiled per time bin; interior bins cover the whole offset range.
        Returns (bins int32[n], lo int64[n], hi int64[n]) with inclusive
        offsets.

        Query-side semantics: endpoints extending past the representable
        range are *clamped* into it (a query reaching before the epoch or
        past the max bin is still answerable over its in-range portion) —
        only ingest (`to_binned`) rejects out-of-range instants.
        """
        if lo_millis > hi_millis:
            raise ValueError(f"inverted interval: {lo_millis} > {hi_millis}")
        # last true millisecond of bin MAX_BIN: MAX_OFFSET over-states short
        # months/non-leap years, so derive the ceiling from the next bin start
        max_millis = int(self.from_binned(MAX_BIN + 1, 0)) - 1
        lo_millis = min(max(int(lo_millis), 0), max_millis)
        hi_millis = min(max(int(hi_millis), 0), max_millis)
        lo_b = self.to_binned(lo_millis)
        hi_b = self.to_binned(hi_millis)
        b0 = int(lo_b.bin)
        b1 = int(hi_b.bin)
        bins = np.arange(b0, b1 + 1, dtype=np.int32)
        lo = np.zeros(len(bins), dtype=np.int64)
        hi = np.full(len(bins), self.max_offset, dtype=np.int64)
        lo[0] = int(lo_b.offset)
        hi[-1] = int(hi_b.offset)
        return bins, lo, hi
