"""Avro interop: feature batches as Avro Object Container Files.

Reference: geomesa-feature-avro (/root/reference/geomesa-features/
geomesa-feature-avro/src/main/scala/org/locationtech/geomesa/features/
avro/ — AvroSimpleFeatureTypeSchema, serialization/AvroUserDataSerializer)
writes features as Avro records: feature id in a reserved field, scalar
attributes as native Avro types, Date as timestamp-millis long, geometry
as WKB bytes. This module implements the same wire layout from scratch
(no avro wheel in the image): the Avro 1.x binary encoding (zigzag-varint
longs, length-prefixed bytes/strings, null-union index prefixes) and the
Object Container File framing (magic, metadata map with embedded JSON
schema, 16-byte sync marker, counted data blocks — Avro spec §
"Object Container Files"), codec null.

Per-row encode/decode is inherent to Avro's varint framing — this is an
interop boundary, not the scan hot path.
"""

from __future__ import annotations

import io
import json
import struct
from typing import IO

import numpy as np

from geomesa_tpu import geometry as geo
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.filter.predicates import PointColumn
from geomesa_tpu.io.varint import append_uvarint as _append_uvarint
from geomesa_tpu.io.varint import read_uvarint as _read_uvarint
from geomesa_tpu.io.varint import unzigzag as _unzigzag
from geomesa_tpu.io.varint import zigzag as _zigzag
from geomesa_tpu.sft import FeatureType

MAGIC = b"Obj\x01"
SYNC = bytes(range(16))  # deterministic marker: files are reproducible
FID_FIELD = "__fid__"  # reference AvroSimpleFeatureUtils.FEATURE_ID_AVRO_FIELD_NAME

_AVRO_TYPES = {
    "Integer": "int",
    "Int": "int",
    "Long": "long",
    "Float": "float",
    "Double": "double",
    "Boolean": "boolean",
    "String": "string",
    "UUID": "string",
    "Bytes": "bytes",
}


def schema_dict(sft: FeatureType) -> dict:
    """The Avro record schema for a feature type (geometry = WKB bytes,
    Date = timestamp-millis long; nullable attributes as null unions)."""
    fields = [{"name": FID_FIELD, "type": "string"}]
    for a in sft.attributes:
        if a.is_geometry:
            t: object = "bytes"
        elif a.type == "Date":
            t = {"type": "long", "logicalType": "timestamp-millis"}
        else:
            t = _AVRO_TYPES[a.type]
        fields.append({"name": a.name, "type": ["null", t]})
    return {
        "type": "record",
        "name": sft.name or "feature",
        "namespace": "org.geomesa.tpu",
        "fields": fields,
        # custom schema attribute naming the geometry field, so a reader
        # without the FeatureType can rebuild it unambiguously (the
        # reference stores the full sft spec in schema props the same way)
        "geomesa.geom": sft.geom_field,
    }


# ----------------------------------------------------------------- encode


def _write_long(out: io.BytesIO, n: int) -> None:
    buf = bytearray()
    _append_uvarint(buf, _zigzag(int(n)))
    out.write(bytes(buf))


def _write_bytes(out: io.BytesIO, b: bytes) -> None:
    _write_long(out, len(b))
    out.write(b)


def _write_str(out: io.BytesIO, s: str) -> None:
    _write_bytes(out, s.encode("utf-8"))


def _encoder_for(a) -> "tuple":
    """(union_branch_writer) for one attribute: returns a fn(out, value)."""
    if a.is_geometry:
        return lambda out, v: _write_bytes(out, geo.to_wkb(v))
    t = a.type
    if t == "Date":
        return lambda out, v: _write_long(out, int(v))
    if t in ("Integer", "Int", "Long"):
        return lambda out, v: _write_long(out, int(v))
    if t == "Float":
        return lambda out, v: out.write(struct.pack("<f", float(v)))
    if t == "Double":
        return lambda out, v: out.write(struct.pack("<d", float(v)))
    if t == "Boolean":
        return lambda out, v: out.write(b"\x01" if v else b"\x00")
    if t == "Bytes":
        return lambda out, v: _write_bytes(out, bytes(v))
    return lambda out, v: _write_str(out, str(v))


def write_avro(fc: FeatureCollection, fh: IO | None = None, block_rows: int = 4096) -> bytes:
    """Serialize a collection as an Avro Object Container File."""
    sft = fc.sft
    schema = schema_dict(sft)
    out = io.BytesIO()
    out.write(MAGIC)
    # file metadata map: one block of 2 entries, then end-of-blocks 0
    _write_long(out, 2)
    _write_str(out, "avro.schema")
    _write_bytes(out, json.dumps(schema).encode("utf-8"))
    _write_str(out, "avro.codec")
    _write_bytes(out, b"null")
    _write_long(out, 0)
    out.write(SYNC)

    encoders = [(a, _encoder_for(a)) for a in sft.attributes]
    geom_field = sft.geom_field
    ids = np.asarray(fc.ids, dtype=str)
    cols = {
        a.name: (fc.columns[a.name] if a.name != geom_field else fc.geom_column)
        for a in sft.attributes
    }
    point = isinstance(fc.geom_column, PointColumn)

    n = len(fc)
    for start in range(0, n, block_rows):
        stop = min(start + block_rows, n)
        body = io.BytesIO()
        for i in range(start, stop):
            _write_str(body, str(ids[i]))
            for a, enc in encoders:
                if a.name == geom_field:
                    g = (
                        geo.Point(float(cols[a.name].x[i]), float(cols[a.name].y[i]))
                        if point
                        else cols[a.name].geometry(i)
                    )
                    _write_long(body, 1)  # union branch 1 = value
                    _write_bytes(body, geo.to_wkb(g))
                    continue
                v = cols[a.name][i]
                if v is None or (isinstance(v, float) and np.isnan(v) and a.type == "String"):
                    _write_long(body, 0)  # union branch 0 = null
                else:
                    _write_long(body, 1)
                    enc(body, v)
        payload = body.getvalue()
        _write_long(out, stop - start)
        _write_long(out, len(payload))
        out.write(payload)
        out.write(SYNC)

    data = out.getvalue()
    if fh is not None:
        fh.write(data)
    return data


# ----------------------------------------------------------------- decode


def _block_count(r: "_Reader") -> int:
    """Data-block row count; a negative count (spec: skippable blocks)
    carries abs(count) rows preceded by a byte size."""
    n = r.read_long()
    if n < 0:
        r.read_long()  # block byte size
        return -n
    r.read_long()  # serialized size
    return n

class _Reader:
    def __init__(self, data: bytes):
        self.b = data
        self.pos = 0

    def read(self, n: int) -> bytes:
        out = self.b[self.pos : self.pos + n]
        if len(out) != n:
            raise ValueError("truncated avro file")
        self.pos += n
        return out

    def read_long(self) -> int:
        acc, self.pos = _read_uvarint(self.b, self.pos)
        return _unzigzag(acc)

    def read_bytes(self) -> bytes:
        return self.read(self.read_long())

    def read_str(self) -> str:
        return self.read_bytes().decode("utf-8")


def _decoder_for(avro_type) -> "object":
    """Value decoder for the schema subset write_avro emits."""
    if isinstance(avro_type, dict):
        avro_type = avro_type["type"]
    try:
        return {
            "string": _Reader.read_str,
            "bytes": _Reader.read_bytes,
            "int": _Reader.read_long,
            "long": _Reader.read_long,
            "float": lambda r: struct.unpack("<f", r.read(4))[0],
            "double": lambda r: struct.unpack("<d", r.read(8))[0],
            "boolean": lambda r: r.read(1) == b"\x01",
        }[avro_type]
    except KeyError:
        raise ValueError(f"unsupported avro type {avro_type!r}") from None


def _field_decoder(avro_type):
    """(decode fn(_Reader) -> value | None) for a field type, handling
    unions in any branch order: the union index picks the branch, null
    branches decode to None (Avro spec: unions encode a long index then
    the value)."""
    if isinstance(avro_type, list):
        branches = [
            None if b == "null" else _decoder_for(b) for b in avro_type
        ]

        def dec(r):
            i = r.read_long()
            if not 0 <= i < len(branches):
                raise ValueError(f"union index {i} out of range")
            b = branches[i]
            return None if b is None else b(r)

        return dec
    return _decoder_for(avro_type)


def _union_value_type(t):
    """The non-null type of a field declaration (union or plain)."""
    if isinstance(t, list):
        vals = [b for b in t if b != "null"]
        if len(vals) != 1:
            raise ValueError(f"unsupported multi-type union {t!r}")
        return vals[0]
    return t


def read_avro(data: "bytes | IO", sft: FeatureType | None = None) -> FeatureCollection:
    """Parse an Object Container File produced by ``write_avro`` (or any
    writer of the same schema subset) back into a FeatureCollection.

    ``sft``: target feature type; when None, a type is rebuilt from the
    embedded schema (geometry comes back as the generic ``Geometry`` type).
    """
    if hasattr(data, "read"):
        data = data.read()
    r = _Reader(bytes(data))
    if r.read(4) != MAGIC:
        raise ValueError("not an avro object container file")
    meta: dict = {}
    while True:
        count = r.read_long()
        if count == 0:
            break
        if count < 0:  # spec: negative count precedes a byte size
            r.read_long()
            count = -count
        for _ in range(count):
            key = r.read_str()
            meta[key] = r.read_bytes()
    codec = meta.get("avro.codec", b"null")
    if codec not in (b"null", b""):
        raise ValueError(f"unsupported avro codec {codec!r}")
    schema = json.loads(meta["avro.schema"].decode("utf-8"))
    sync = r.read(16)

    fields = schema["fields"]
    if fields[0]["name"] != FID_FIELD:
        raise ValueError(
            f"expected leading {FID_FIELD!r} feature-id field, got {fields[0]['name']!r}"
        )
    if sft is None:
        sft = _sft_from_schema(schema)
    geom_field = sft.geom_field

    decoders = [(f["name"], _field_decoder(f["type"])) for f in fields[1:]]

    ids: list = []
    rows: list = []
    while r.pos < len(r.b):
        n_rows = _block_count(r)
        for _ in range(n_rows):
            ids.append(r.read_str())
            row = {}
            for name, dec in decoders:
                v = dec(r)
                if v is not None and name == geom_field:
                    v = geo.from_wkb(v)
                row[name] = v
            rows.append(row)
        if r.read(16) != sync:
            raise ValueError("sync marker mismatch: corrupt avro block")
    return FeatureCollection.from_rows(sft, rows, ids=ids)


def read_records(data: "bytes | IO"):
    """(schema dict, list of plain-dict records) from a container file —
    the generic record view for the Avro ingest converter (reference
    geomesa-convert-avro): geometry/bytes values stay raw ``bytes``, the
    feature id is under ``__fid__``."""
    if hasattr(data, "read"):
        data = data.read()
    r = _Reader(bytes(data))
    if r.read(4) != MAGIC:
        raise ValueError("not an avro object container file")
    meta: dict = {}
    while True:
        count = r.read_long()
        if count == 0:
            break
        if count < 0:
            r.read_long()
            count = -count
        for _ in range(count):
            key = r.read_str()
            meta[key] = r.read_bytes()
    if meta.get("avro.codec", b"null") not in (b"null", b""):
        raise ValueError("unsupported avro codec")
    schema = json.loads(meta["avro.schema"].decode("utf-8"))
    sync = r.read(16)
    decoders = [(f["name"], _field_decoder(f["type"])) for f in schema["fields"]]
    records = []
    while r.pos < len(r.b):
        n_rows = _block_count(r)
        for _ in range(n_rows):
            records.append({name: dec(r) for name, dec in decoders})
        if r.read(16) != sync:
            raise ValueError("sync marker mismatch: corrupt avro block")
    return schema, records


def _sft_from_schema(schema: dict) -> FeatureType:
    """Rebuild a FeatureType from the embedded Avro schema."""
    rev = {v: k for k, v in _AVRO_TYPES.items() if k not in ("Int", "UUID")}
    geom_name = schema.get("geomesa.geom")
    bytes_fields = [
        f["name"]
        for f in schema["fields"][1:]
        if _union_value_type(f["type"]) == "bytes"
    ]
    if geom_name is None and len(bytes_fields) == 1:
        geom_name = bytes_fields[0]  # unambiguous: the geomesa layout uses
        # bytes for WKB geometry
    if geom_name is None and bytes_fields:
        raise ValueError(
            "schema has multiple bytes fields and no geomesa.geom marker: "
            "pass the FeatureType explicitly"
        )
    parts = []
    for f in schema["fields"][1:]:
        t = _union_value_type(f["type"])
        if f["name"] == geom_name:
            parts.append(f"*{f['name']}:Geometry:srid=4326")
        elif isinstance(t, dict) and t.get("logicalType") == "timestamp-millis":
            parts.append(f"{f['name']}:Date")
        else:
            parts.append(f"{f['name']}:{rev[t]}")
    return FeatureType.from_spec(schema.get("name", "feature"), ",".join(parts))
