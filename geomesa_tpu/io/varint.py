"""Shared zigzag + LEB128 varint primitives.

One definition for the binary codecs that use zigzag varints — Avro
(io/avro.py container files) and TWKB (io/twkb.py geometries) — so the
bit-twiddling can't drift between them."""

from __future__ import annotations


def zigzag(v: int) -> int:
    return (v << 1) ^ (v >> 63)


def unzigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def append_uvarint(out: bytearray, v: int) -> None:
    """LEB128-encode a (already zigzagged, non-negative) value."""
    v &= (1 << 64) - 1
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def read_uvarint(data: bytes, pos: int) -> tuple[int, int]:
    """(value, new_pos) — inverse of append_uvarint."""
    shift = 0
    v = 0
    while True:
        b = data[pos]
        pos += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, pos
        shift += 7
