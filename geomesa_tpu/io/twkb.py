"""TWKB ("tiny well-known binary") geometry codec.

Reference: geomesa-features TwkbSerialization (/root/reference/
geomesa-features/geomesa-feature-common/src/main/scala/org/locationtech/
geomesa/features/serialization/TwkbSerialization.scala) — GeoMesa's
compact on-disk geometry encoding. Implemented from the public TWKB
format description: a type+precision header byte (zigzag precision in
the high nibble), a metadata byte, then coordinates as zigzag varint
*deltas* of the scaled integer coordinates. Integer deltas make
serialized tracks/polygons a fraction of WKB's fixed 8-byte doubles.

Supports the geometry kinds of geomesa_tpu.geometry; bbox/size/id-list
metadata flags are not written (and rejected on read, like unknown WKB
variants — the reference likewise writes plain TWKB)."""

from __future__ import annotations

import numpy as np

from geomesa_tpu import geometry as geo

_EMPTY = 0x10

_TYPE_CODES = {
    geo.Point: 1,
    geo.LineString: 2,
    geo.Polygon: 3,
    geo.MultiPoint: 4,
    geo.MultiLineString: 5,
    geo.MultiPolygon: 6,
}


from geomesa_tpu.io.varint import (
    append_uvarint as _write_varint,
    read_uvarint as _read_varint,
    unzigzag as _unzigzag,
    zigzag as _zigzag,
)


class _CoordWriter:
    """Delta state shared across all rings of one geometry (per spec)."""

    def __init__(self, scale: float):
        self.scale = scale
        self.prev = np.zeros(2, dtype=np.int64)

    def write(self, out: bytearray, coords: np.ndarray) -> None:
        q = np.round(np.asarray(coords, dtype=np.float64) * self.scale).astype(
            np.int64
        )
        for row in q:
            for d in range(2):
                _write_varint(out, _zigzag(int(row[d] - self.prev[d])))
                self.prev[d] = row[d]


class _CoordReader:
    def __init__(self, scale: float):
        self.scale = scale
        self.prev = [0, 0]

    def read(self, data: bytes, pos: int, n: int) -> tuple[np.ndarray, int]:
        out = np.empty((n, 2), dtype=np.float64)
        for i in range(n):
            for d in range(2):
                zz, pos = _read_varint(data, pos)
                self.prev[d] += _unzigzag(zz)
                out[i, d] = self.prev[d] / self.scale
        return out, pos


def to_twkb(g: geo.Geometry, precision: int = 7) -> bytes:
    """Encode one geometry; ``precision`` decimal digits (zigzagged into
    the header's high nibble, range -8..7)."""
    if not -8 <= precision <= 7:
        raise ValueError("twkb precision must be in [-8, 7]")
    code = _TYPE_CODES.get(type(g))
    if code is None:
        raise ValueError(f"cannot twkb-encode {type(g).__name__}")
    out = bytearray()
    out.append((_zigzag(precision) << 4) | code)
    scale = 10.0 ** precision
    w = _CoordWriter(scale)
    if isinstance(g, geo.Point):
        out.append(0)
        w.write(out, np.array([[g.x, g.y]]))
    elif isinstance(g, geo.LineString):
        out.append(0)  # the LineString type requires >= 2 points
        _write_varint(out, len(g.coords))
        w.write(out, g.coords)
    elif isinstance(g, geo.Polygon):
        rings = [g.shell] + list(g.holes)
        out.append(0)
        _write_varint(out, len(rings))
        for r in rings:
            _write_varint(out, len(r))
            w.write(out, r)
    else:  # multi-geometries
        parts = list(g.parts)
        out.append(0 if parts else _EMPTY)
        if parts:
            _write_varint(out, len(parts))
            for p in parts:
                if isinstance(p, geo.Point):
                    w.write(out, np.array([[p.x, p.y]]))
                elif isinstance(p, geo.LineString):
                    _write_varint(out, len(p.coords))
                    w.write(out, p.coords)
                else:
                    rings = [p.shell] + list(p.holes)
                    _write_varint(out, len(rings))
                    for r in rings:
                        _write_varint(out, len(r))
                        w.write(out, r)
    return bytes(out)


def from_twkb(data: bytes) -> geo.Geometry:
    """Decode one TWKB geometry."""
    code = data[0] & 0x0F
    precision = _unzigzag(data[0] >> 4)
    meta = data[1]
    if meta & ~_EMPTY:
        raise ValueError(f"unsupported twkb metadata flags: {meta:#x}")
    if meta & _EMPTY and code not in (4, 5, 6):
        # the geometry model has no empty scalar geometries (LineString
        # requires >= 2 points etc.) — reject e.g. POINT EMPTY cleanly
        raise ValueError(f"empty twkb geometry (type {code}) not supported")
    pos = 2
    scale = 10.0 ** precision
    r = _CoordReader(scale)
    if code == 1:
        c, pos = r.read(data, pos, 1)
        return geo.Point(c[0, 0], c[0, 1])
    if code == 2:
        n, pos = _read_varint(data, pos)
        c, pos = r.read(data, pos, n)
        return geo.LineString(c)
    if code == 3:
        nrings, pos = _read_varint(data, pos)
        rings = []
        for _ in range(nrings):
            n, pos = _read_varint(data, pos)
            c, pos = r.read(data, pos, n)
            rings.append(c)
        return geo.Polygon(rings[0], rings[1:])
    if code in (4, 5, 6):
        cls = {4: geo.MultiPoint, 5: geo.MultiLineString, 6: geo.MultiPolygon}[code]
        if meta & _EMPTY:
            return cls([])
        nparts, pos = _read_varint(data, pos)
        parts = []
        for _ in range(nparts):
            if code == 4:
                c, pos = r.read(data, pos, 1)
                parts.append(geo.Point(c[0, 0], c[0, 1]))
            elif code == 5:
                n, pos = _read_varint(data, pos)
                c, pos = r.read(data, pos, n)
                parts.append(geo.LineString(c))
            else:
                nrings, pos = _read_varint(data, pos)
                rings = []
                for _ in range(nrings):
                    n, pos = _read_varint(data, pos)
                    c, pos = r.read(data, pos, n)
                    rings.append(c)
                parts.append(geo.Polygon(rings[0], rings[1:]))
        return cls(parts)
    raise ValueError(f"unknown twkb type code {code}")
