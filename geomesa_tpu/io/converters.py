"""Ingest converters: config-driven parsing of delimited text and JSON
into feature batches.

Reference: geomesa-convert (/root/reference/geomesa-convert/
geomesa-convert-common/src/main/scala/org/locationtech/geomesa/convert2/
SimpleFeatureConverter.scala:28, transforms/Expression.scala,
TypeInference.scala). The reference's HOCON config + expression DSL maps
to a Converter built from field specs using the same expression shapes:

    $1                      column reference (1-based, $0 = whole record)
    $1::int  $2::double     casts (::int ::long ::double ::string)
    point($1, $2)           geometry constructors (also geomFromWkt($1))
    datetime($3)            ISO-8601 -> epoch millis
    concat($1, '-', $2)     string concat; 'lit' literals
    md5($1) / uuid()        id functions

JSON records address fields with $.a.b paths instead of $N.
Type inference (``infer_schema``) mirrors TypeInference: trial-parse
columns as int -> double -> date -> string, geometry from lon/lat pairs.
"""

from __future__ import annotations

import csv as _csv
import hashlib
import io
import json
import re
import uuid as _uuid
from dataclasses import dataclass, field
from typing import Callable, Sequence

import numpy as np

from geomesa_tpu import geometry as geo
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType

# -- expression DSL ------------------------------------------------------

_TOKEN = re.compile(
    r"\s*(?:(?P<col>\$\d+)|(?P<path>\$(?:\.@?\w+)+)|(?P<name>\w+)\s*\(|(?P<lit>'[^']*')"
    r"|(?P<num>-?\d+(?:\.\d+)?)|(?P<ident>\w+)|(?P<close>\))|(?P<comma>,)|(?P<cast>::\w+))"
)


@dataclass
class Expression:
    """A compiled field expression: record -> value."""

    fn: Callable
    text: str

    def __call__(self, rec):
        return self.fn(rec)


def _get_path(rec, path: Sequence[str]):
    cur = rec
    for p in path:
        if cur is None:
            return None
        cur = cur.get(p) if isinstance(cur, dict) else None
    return cur


_CASTS = {
    "int": lambda v: int(float(v)),
    "long": lambda v: int(float(v)),
    "float": float,
    "double": float,
    "string": str,
}


def _compile_fns(name: str, args: list):
    if name == "point":
        return lambda rec: geo.Point(float(args[0](rec)), float(args[1](rec)))
    if name in ("geomfromwkt", "geometry"):
        return lambda rec: geo.from_wkt(str(args[0](rec)))
    if name == "geomfromwkb":
        return lambda rec: geo.from_wkb(args[0](rec))
    if name in ("datetime", "date", "isodate"):
        from geomesa_tpu.filter.ecql import parse_dt_millis

        return lambda rec: parse_dt_millis(str(args[0](rec)))
    if name == "millisecondstodate":
        return lambda rec: int(args[0](rec))
    if name == "concat":
        return lambda rec: "".join(str(a(rec)) for a in args)
    if name in ("tolowercase", "lowercase"):
        return lambda rec: str(args[0](rec)).lower()
    if name in ("touppercase", "uppercase"):
        return lambda rec: str(args[0](rec)).upper()
    if name == "trim":
        return lambda rec: str(args[0](rec)).strip()
    if name == "md5":
        return lambda rec: hashlib.md5(str(args[0](rec)).encode()).hexdigest()
    if name == "uuid":
        return lambda rec: str(_uuid.uuid4())
    if name.startswith("st_"):
        # the ST_ function library (sql.functions) is shared with query
        # transforms: st_x(geom), st_buffer(geom, 1), ... evaluate over
        # the record's geometry values
        from geomesa_tpu.sql.functions import FUNCTIONS

        fn = FUNCTIONS.get(name)
        if fn is not None:
            return lambda rec: fn(*(a(rec) for a in args))
    raise ValueError(f"unknown transform function {name!r}")


def compile_expression(text: str) -> Expression:
    """Compile one expression string into a callable."""
    pos = 0

    def parse() -> Callable:
        nonlocal pos
        m = _TOKEN.match(text, pos)
        if m is None:
            raise ValueError(f"bad expression at {text[pos:]!r}")
        pos = m.end()
        if m.group("col"):
            idx = int(m.group("col")[1:])
            base = (lambda rec: rec[idx - 1]) if idx > 0 else (lambda rec: rec)
        elif m.group("path"):
            path = m.group("path")[2:].split(".")
            base = lambda rec: _get_path(rec, path)
        elif m.group("lit"):
            v = m.group("lit")[1:-1]
            base = lambda rec: v
        elif m.group("num"):
            v = float(m.group("num")) if "." in m.group("num") else int(m.group("num"))
            base = lambda rec: v
        elif m.group("ident"):
            # bare identifier: a record-field reference by name (query
            # transforms evaluate over {attribute: value} row dicts).
            # Unknown names raise — a typo must not fabricate a column
            key = m.group("ident")

            def _field(rec, key=key):
                if isinstance(rec, dict):
                    if key not in rec:
                        raise KeyError(f"unknown field {key!r} in expression")
                    return rec[key]
                raise ValueError(
                    f"bare identifier {key!r} needs a named record; use "
                    "$N for positional fields"
                )

            base = _field
        elif m.group("name"):
            fname = m.group("name").lower()
            args: list = []
            while True:
                m2 = _TOKEN.match(text, pos)
                if m2 and m2.group("close"):
                    pos = m2.end()
                    break
                args.append(parse())
                m3 = _TOKEN.match(text, pos)
                if m3 and m3.group("comma"):
                    pos = m3.end()
                elif m3 and m3.group("close"):
                    pos = m3.end()
                    break
                else:
                    raise ValueError(f"expected , or ) at {text[pos:]!r}")
            base = _compile_fns(fname, args)
        else:
            raise ValueError(f"bad expression at {text[pos:]!r}")
        # optional cast suffix
        m4 = _TOKEN.match(text, pos)
        if m4 and m4.group("cast"):
            pos = m4.end()
            cast = _CASTS.get(m4.group("cast")[2:].lower())
            if cast is None:
                raise ValueError(f"unknown cast {m4.group('cast')!r}")
            inner = base
            base = lambda rec: cast(inner(rec))
        return base

    fn = parse()
    if pos != len(text) and text[pos:].strip():
        raise ValueError(f"trailing input in expression: {text[pos:]!r}")
    return Expression(fn, text)


# -- converter -----------------------------------------------------------

@dataclass
class FieldSpec:
    name: str
    transform: str  # expression string


@dataclass
class Converter:
    """Config-driven converter: parse records, evaluate field expressions,
    emit a FeatureCollection (reference SimpleFeatureConverter.process)."""

    sft: FeatureType
    fields: Sequence[FieldSpec]
    id_field: str | None = None  # expression; None = running index
    fmt: str = "delimited"  # "delimited" | "json" | "xml" | "fixed-width"
    delimiter: str = ","
    skip_lines: int = 0  # header rows to drop (delimited / fixed-width)
    drop_errors: bool = True  # skip unparseable/invalid records vs raise
    # converted-row validation (the reference CqlValidatorFactory hook;
    # io.validators): a spec string ("index", "has-geo,z-bounds", ...),
    # a sequence of names/Validator objects, or None. Failures count per
    # reason in ``error_reasons`` and follow ``drop_errors`` skip/raise.
    validators: "str | Sequence | None" = None
    # xml: tag of the per-feature element (reference geomesa-convert-xml
    # featurePath); fields address the element tree with $.child.grandchild
    # paths, attributes as @name segments ($.pos.@lat)
    xml_feature_tag: str | None = None
    # fixed-width: (start, width) character slices per column (reference
    # geomesa-convert-fixedwidth FixedWidthConverter); $N addresses the
    # N-th slice, stripped
    fixed_widths: Sequence[tuple[int, int]] | None = None

    def __post_init__(self):
        from geomesa_tpu.io.validators import parse_validators

        self._exprs = [(f.name, compile_expression(f.transform)) for f in self.fields]
        self._id_expr = compile_expression(self.id_field) if self.id_field else None
        self._validators = parse_validators(self.validators, self.sft)
        self.errors = 0
        self.error_reasons: dict = {}

    def convert(self, data: "str | bytes | io.IOBase") -> FeatureCollection:
        if self.fmt == "avro":  # binary format: never decode
            if hasattr(data, "read"):
                data = data.read()
        else:
            if isinstance(data, bytes):
                data = data.decode("utf-8")
            if not isinstance(data, str):
                data = data.read()
                if isinstance(data, bytes):
                    data = data.decode("utf-8")
        return self.convert_records(self._parse(data))

    def convert_records(self, records) -> FeatureCollection:
        """Convert an iterable of already-parsed records (lists for $N
        expressions, dicts for $.path expressions). The entry point for
        externally-sourced records — e.g. DB-API rows via
        :func:`dbapi_records` (the geomesa-convert-jdbc analogue)."""
        rows = []
        ids = []
        self.errors = 0
        self.error_reasons = {}

        def reject(reason: str) -> None:
            self.errors += 1
            self.error_reasons[reason] = self.error_reasons.get(reason, 0) + 1

        for i, rec in enumerate(records):
            try:
                row = {name: expr(rec) for name, expr in self._exprs}
                rid = str(self._id_expr(rec)) if self._id_expr else str(i)
            except Exception:
                if self.drop_errors:
                    reject("parse")
                    continue
                raise
            failed = None
            for v in self._validators:
                reason = v.validate(row)
                if reason is not None:
                    failed = f"{v.name}: {reason}"
                    break
            if failed is not None:
                if self.drop_errors:
                    reject(failed)
                    continue
                raise ValueError(f"validation failed ({failed}): record {i}")
            rows.append(row)
            ids.append(rid)
        return FeatureCollection.from_rows(self.sft, rows, ids=ids)

    def _parse(self, data: str):
        if self.fmt == "delimited":
            reader = _csv.reader(io.StringIO(data), delimiter=self.delimiter)
            for i, rec in enumerate(reader):
                if i < self.skip_lines or not rec:
                    continue
                yield rec
        elif self.fmt == "fixed-width":
            if not self.fixed_widths:
                raise ValueError("fixed-width converter requires fixed_widths")
            for i, line in enumerate(io.StringIO(data)):
                line = line.rstrip("\n")
                if i < self.skip_lines or not line.strip():
                    continue
                yield [line[s : s + w].strip() for s, w in self.fixed_widths]
        elif self.fmt == "json":
            doc = json.loads(data)
            if isinstance(doc, dict):
                doc = [doc]
            yield from doc
        elif self.fmt == "avro":
            from geomesa_tpu.io.avro import read_records

            _, records = read_records(data)
            yield from records
        elif self.fmt == "xml":
            import xml.etree.ElementTree as ET

            if self.xml_feature_tag is None:
                raise ValueError("xml converter requires xml_feature_tag")
            root = ET.fromstring(data)
            elems = (
                [root]
                if _local(root.tag) == self.xml_feature_tag
                else [
                    e for e in root.iter() if _local(e.tag) == self.xml_feature_tag
                ]
            )
            for e in elems:
                yield _elem_to_dict(e)
        else:
            raise ValueError(f"unknown converter format {self.fmt!r}")


# -- xml record shaping --------------------------------------------------


def _local(tag: str) -> str:
    """Element tag without its namespace ({uri}tag -> tag)."""
    return tag.rsplit("}", 1)[-1]


def _elem_to_dict(e) -> dict:
    """An XML element as a nested dict the $.path expressions can address:
    attributes under '@name', leaf children under their tag (text), nested
    children recurse; the first occurrence of a repeated tag wins (the
    reference's xpath configs select explicitly — this covers the common
    record-per-element shape)."""
    out: dict = {f"@{k}": v for k, v in e.attrib.items()}
    for c in e:
        tag = _local(c.tag)
        if tag in out:
            continue
        out[tag] = _elem_to_dict(c) if (len(c) or c.attrib) else (c.text or "").strip()
    if not out and e.text:
        return e.text.strip()
    return out


# -- type inference ------------------------------------------------------

def infer_schema(
    name: str,
    rows: Sequence[Sequence[str]],
    header: Sequence[str] | None = None,
) -> tuple[FeatureType, Converter]:
    """Infer a schema + converter from delimited sample rows (reference
    TypeInference.scala): trial-parse int -> double -> date -> string;
    adjacent lon/lat-range double columns become the point geometry."""
    if not rows:
        raise ValueError("no sample rows")
    n_cols = len(rows[0])
    names = list(header) if header else [f"col{i}" for i in range(n_cols)]
    kinds = []
    for c in range(n_cols):
        vals = [r[c] for r in rows if len(r) > c]
        kinds.append(_infer_kind(vals))
    # geometry: a name-hinted (lon, lat) numeric pair wins; otherwise the
    # first adjacent in-range Double pair (rows may be ragged; only rows
    # long enough vote). Int-only pairs need the name hint — bare small-int
    # columns (counts, ages) would false-positive on the range test.
    lon_names = {"lon", "long", "longitude", "x"}
    lat_names = {"lat", "latitude", "y"}

    def in_range(c) -> bool:
        full = [r for r in rows if len(r) > c + 1]
        if not full:
            return False
        xs = np.array([float(r[c]) for r in full])
        ys = np.array([float(r[c + 1]) for r in full])
        return bool((np.abs(xs) <= 180).all() and (np.abs(ys) <= 90).all())

    geom_pair = None
    for c in range(n_cols - 1):
        if (
            names[c].lower() in lon_names
            and names[c + 1].lower() in lat_names
            and kinds[c] in ("Int", "Double")
            and kinds[c + 1] in ("Int", "Double")
            and in_range(c)
        ):
            geom_pair = c
            break
    if geom_pair is None:
        for c in range(n_cols - 1):
            if kinds[c] == "Double" and kinds[c + 1] == "Double" and in_range(c):
                geom_pair = c
                break
    parts = []
    fields = []
    for c in range(n_cols):
        if geom_pair is not None and c == geom_pair:
            parts.append("*geom:Point:srid=4326")
            fields.append(FieldSpec("geom", f"point(${c + 1}, ${c + 2})"))
            continue
        if geom_pair is not None and c == geom_pair + 1:
            continue
        t = kinds[c]
        spec_t = {"Int": "Integer", "Double": "Double", "Date": "Date"}.get(t, "String")
        parts.append(f"{names[c]}:{spec_t}")
        cast = {"Int": "::int", "Double": "::double"}.get(t, "")
        expr = f"datetime(${c + 1})" if t == "Date" else f"${c + 1}{cast}"
        fields.append(FieldSpec(names[c], expr))
    sft = FeatureType.from_spec(name, ",".join(parts))
    return sft, Converter(sft=sft, fields=fields)


_DATE_RE = re.compile(r"^\d{4}-\d{2}-\d{2}([T ]\d{2}:\d{2}(:\d{2})?(\.\d+)?Z?)?$")


def _infer_kind(vals: Sequence[str]) -> str:
    def all_match(fn) -> bool:
        try:
            for v in vals:
                fn(v)
            return True
        except (ValueError, TypeError):
            return False

    if all_match(int):
        return "Int"
    if all_match(float):
        return "Double"
    if all(_DATE_RE.match(str(v)) for v in vals):
        return "Date"
    return "String"


# -- database records (geomesa-convert-jdbc analogue) --------------------

def dbapi_records(conn, sql: str, params=()):
    """Rows of a DB-API 2.0 query as converter records: each row yields
    ``[rowvals...]`` addressable as $1..$N ($0 is the whole row), matching
    the reference's JDBC converter column addressing
    (geomesa-convert-jdbc/.../JdbcConverter.scala: statement.executeQuery,
    fields reference columns by index). Works with any DB-API driver
    (sqlite3 in the standard library).

        conv = Converter(sft, fields=[FieldSpec("name", "$1"), ...])
        fc = conv.convert_records(dbapi_records(conn, "SELECT ..."))
    """
    cur = conn.cursor()
    try:
        cur.execute(sql, params)
        while True:
            batch = cur.fetchmany(10_000)
            if not batch:
                break
            for row in batch:
                yield list(row)  # $1 = first column, $0 = whole row
    finally:
        cur.close()
