"""ORC feature IO — the geomesa-fs ORC storage-format analogue.

Reference: OrcFileSystemStorage (/root/reference/geomesa-fs/
geomesa-fs-storage/geomesa-fs-storage-orc/src/main/scala/org/
locationtech/geomesa/fs/storage/orc/OrcFileSystemStorage.scala,
OrcSearchArguments.scala). Same column layout as io/parquet (points as
flat ``<geom>_x``/``<geom>_y`` doubles, extents as WKB binary), written
through pyarrow.orc.

pyarrow's ORC writer cannot store user metadata in the file footer, so —
exactly like the reference FSDS keeps schema/partition state in separate
metadata files (fs/storage/common/metadata/FileBasedMetadata.scala) — the
SFT spec rides in a ``<path>.sft.json`` sidecar, and :class:`OrcStorage`
keeps a directory-level ``_metadata.json`` with per-file bboxes for
file-granularity bbox push-down (the OrcSearchArguments analogue:
pyarrow exposes no stripe-statistics filter, so pruning happens at the
file level and the residual bbox filters vectorized after read).
"""

from __future__ import annotations

import json
import os

import numpy as np

from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType


def _sidecar(path) -> str:
    return f"{path}.sft.json"


def write_orc(fc: FeatureCollection, path, compression: str = "zstd") -> None:
    """Write a collection to one ORC file plus a ``.sft.json`` schema
    sidecar."""
    import pyarrow.orc as orc

    from geomesa_tpu.io.arrow import flat_point_table

    # ORC's own dictionary encoding handles strings; arrow dictionary
    # columns would round-trip as plain strings anyway
    orc.write_table(
        flat_point_table(fc, dictionary=False), path,
        compression=compression.upper(),
    )
    if isinstance(path, (str, os.PathLike)):  # file-likes get no sidecar
        with open(_sidecar(path), "w") as f:
            json.dump({"name": fc.sft.name, "spec": fc.sft.to_spec()}, f)


def read_orc(
    path,
    sft: "FeatureType | None" = None,
    bbox: "tuple[float, float, float, float] | None" = None,
) -> FeatureCollection:
    """Read an ORC file written by :func:`write_orc`. ``bbox`` applies a
    vectorized coordinate filter after the read: exact containment for
    point schemas, bbox-intersects on per-geometry bounds for extent
    schemas (the reader-side loose filter; exact predicates belong to the
    query path). File-level pruning lives in :class:`OrcStorage`, where
    per-file extents are known."""
    import pyarrow.orc as orc

    if sft is None:
        side = _sidecar(path)
        if not os.path.exists(side):
            raise ValueError(f"no sidecar {side}; pass sft explicitly")
        with open(side) as f:
            meta = json.load(f)
        sft = FeatureType.from_spec(meta["name"], meta["spec"])
    table = orc.ORCFile(path).read()
    from geomesa_tpu.io.arrow import table_to_collection

    fc = table_to_collection(table, sft)
    if bbox is not None:
        geom = sft.geom_field
        x0, y0, x1, y1 = bbox
        if f"{geom}_x" in table.column_names:
            x = np.asarray(table[f"{geom}_x"], dtype=np.float64)
            y = np.asarray(table[f"{geom}_y"], dtype=np.float64)
            fc = fc.mask((x >= x0) & (x <= x1) & (y >= y0) & (y <= y1))
        elif geom is not None:
            b = fc.geom_column.bboxes.astype(np.float64)
            fc = fc.mask(
                (b[:, 0] <= x1) & (b[:, 2] >= x0)
                & (b[:, 1] <= y1) & (b[:, 3] >= y0)
            )
        else:
            raise ValueError("bbox filtering requires a geometry schema")
    return fc


class OrcStorage:
    """Directory of ORC chunk files with per-file bbox metadata: the
    OrcFileSystemStorage partition analogue. ``write`` appends a chunk
    file and records its extent; ``query(bbox)`` reads only files whose
    recorded extent intersects (file-granularity push-down), then applies
    the residual vectorized filter."""

    _META = "_metadata.json"

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._meta_path = os.path.join(root, self._META)
        if os.path.exists(self._meta_path):
            with open(self._meta_path) as f:
                self.meta = json.load(f)
        else:
            self.meta = {"sft": None, "files": []}

    def _save_meta(self) -> None:
        tmp = self._meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.meta, f)
        os.replace(tmp, self._meta_path)

    def write(self, fc: FeatureCollection, compression: str = "zstd") -> str:
        if self.meta["sft"] is None:
            self.meta["sft"] = {"name": fc.sft.name, "spec": fc.sft.to_spec()}
        elif self.meta["sft"]["spec"] != fc.sft.to_spec():
            raise ValueError("schema mismatch with existing storage")
        name = f"chunk-{len(self.meta['files']):06d}.orc"
        path = os.path.join(self.root, name)
        write_orc(fc, path, compression=compression)
        from geomesa_tpu.filter.predicates import PointColumn

        col = fc.geom_column
        if len(fc) == 0 or col is None:
            # inverted infinite extent: prunes against EVERY query box
            bbox = [float("inf"), float("inf"), float("-inf"), float("-inf")]
        elif isinstance(col, PointColumn):
            bbox = [
                float(np.min(col.x)), float(np.min(col.y)),
                float(np.max(col.x)), float(np.max(col.y)),
            ]
        else:  # union of true per-geometry bounds, not representative points
            b = col.bboxes.astype(np.float64)
            bbox = [
                float(b[:, 0].min()), float(b[:, 1].min()),
                float(b[:, 2].max()), float(b[:, 3].max()),
            ]
        self.meta["files"].append({"name": name, "rows": len(fc), "bbox": bbox})
        self._save_meta()
        return path

    @property
    def sft(self) -> FeatureType:
        m = self.meta["sft"]
        if m is None:
            raise ValueError("empty storage")
        return FeatureType.from_spec(m["name"], m["spec"])

    def files(self, bbox=None) -> list[str]:
        """Chunk files, pruned to those whose extent intersects bbox."""
        out = []
        for f in self.meta["files"]:
            if bbox is not None:
                fx0, fy0, fx1, fy1 = f["bbox"]
                x0, y0, x1, y1 = bbox
                if fx1 < x0 or fx0 > x1 or fy1 < y0 or fy0 > y1:
                    continue
            out.append(os.path.join(self.root, f["name"]))
        return out

    def query(self, bbox=None) -> FeatureCollection:
        sft = self.sft
        parts = [read_orc(p, sft=sft, bbox=bbox) for p in self.files(bbox)]
        parts = [p for p in parts if len(p)]
        if not parts:
            return FeatureCollection.from_rows(sft, [])
        if len(parts) == 1:
            return parts[0]
        return FeatureCollection.concat(parts)
