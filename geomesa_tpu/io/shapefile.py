"""Shapefile (.shp/.dbf) reader: ESRI shapefiles -> feature batches.

Reference: geomesa-convert-shp (/root/reference/geomesa-convert/
geomesa-convert-shp/src/main/scala/org/locationtech/geomesa/convert/shp/
ShapefileConverter.scala) — there it delegates to GeoTools' shapefile
store; here the format is decoded directly (no GDAL/fiona in the image):
the .shp geometry file (ESRI whitepaper layout: 100-byte header, BE
record headers, LE shapes) and the dBase III .dbf attribute file
(fixed-width ASCII records). Point/MultiPoint/PolyLine/Polygon shapes
map onto the packed geometry model; polygon ring winding (outer = CW in
shapefiles) splits shells from holes, holes attaching to the preceding
shell (the standard writer ordering).
"""

from __future__ import annotations

import struct
from typing import IO, Optional

import numpy as np

from geomesa_tpu import geometry as geo
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType

SHP_MAGIC = 9994

# shape type code -> handler name
_POINT = {1, 11, 21}  # Point / PointZ / PointM (Z/M dropped)
_POLYLINE = {3, 13, 23}
_POLYGON = {5, 15, 25}
_MULTIPOINT = {8, 18, 28}


def _ring_is_cw(ring: np.ndarray) -> bool:
    """Shoelace: negative signed area = clockwise = shapefile outer ring."""
    x, y = ring[:, 0], ring[:, 1]
    return float(np.sum(x[:-1] * y[1:] - x[1:] * y[:-1])) < 0


def _read_shapes(data: bytes) -> list:
    """.shp payload -> list of Geometry | None (null shapes)."""
    if len(data) < 100 or struct.unpack(">i", data[:4])[0] != SHP_MAGIC:
        raise ValueError("not a shapefile (.shp)")
    out: list = []
    pos = 100
    n = len(data)
    while pos + 8 <= n:
        (_recno, content_words) = struct.unpack(">ii", data[pos : pos + 8])
        pos += 8
        end = pos + content_words * 2
        if end > n:
            raise ValueError("truncated shapefile record")
        (stype,) = struct.unpack("<i", data[pos : pos + 4])
        body = data[pos + 4 : end]
        pos = end
        if stype == 0:
            out.append(None)
        elif stype in _POINT:
            x, y = struct.unpack_from("<2d", body, 0)
            out.append(geo.Point(x, y))
        elif stype in _MULTIPOINT:
            (npts,) = struct.unpack_from("<i", body, 32)
            pts = np.frombuffer(body, "<f8", count=npts * 2, offset=36).reshape(-1, 2)
            out.append(
                geo.MultiPoint([geo.Point(float(p[0]), float(p[1])) for p in pts])
            )
        elif stype in _POLYLINE or stype in _POLYGON:
            nparts, npts = struct.unpack_from("<2i", body, 32)
            parts = np.frombuffer(body, "<i4", count=nparts, offset=40)
            pts = np.frombuffer(
                body, "<f8", count=npts * 2, offset=40 + 4 * nparts
            ).reshape(-1, 2)
            bounds = np.append(parts, npts)
            rings = [
                np.array(pts[bounds[i] : bounds[i + 1]], dtype=np.float64)
                for i in range(nparts)
            ]
            if stype in _POLYLINE:
                lines = [geo.LineString(r) for r in rings if len(r) >= 2]
                out.append(
                    lines[0] if len(lines) == 1 else geo.MultiLineString(lines)
                )
            else:
                out.append(_assemble_polygon(rings))
        else:
            raise ValueError(f"unsupported shape type {stype}")
    return out


def _assemble_polygon(rings: list) -> "geo.Geometry":
    """CW rings open polygons, CCW rings are holes of the preceding shell
    (standard shapefile writer ordering)."""
    polys: list[tuple[np.ndarray, list]] = []
    for r in rings:
        if len(r) < 4:
            continue
        if _ring_is_cw(r) or not polys:
            polys.append((r[::-1].copy(), []))  # store shells CCW (WKT norm)
        else:
            polys[-1][1].append(r)
    if not polys:
        raise ValueError("polygon record with no valid rings")
    geoms = [geo.Polygon(shell, holes) for shell, holes in polys]
    return geoms[0] if len(geoms) == 1 else geo.MultiPolygon(geoms)


def _read_dbf(data: bytes) -> tuple[list[str], list[str], list[list]]:
    """dBase III file -> (field names, field types, record values)."""
    if len(data) < 32:
        raise ValueError("truncated .dbf")
    n_rec, hdr_size, rec_size = struct.unpack_from("<iHH", data, 4)
    fields = []
    pos = 32
    while pos < hdr_size - 1 and data[pos] != 0x0D:
        name = data[pos : pos + 11].split(b"\x00")[0].decode("ascii", "replace")
        ftype = chr(data[pos + 11])
        length = data[pos + 16]
        decimals = data[pos + 17]
        fields.append((name, ftype, length, decimals))
        pos += 32
    names = [f[0] for f in fields]
    kinds = []
    for _, ftype, _length, decimals in fields:
        if ftype in ("N", "F"):
            kinds.append("Double" if (decimals > 0 or ftype == "F") else "Long")
        elif ftype == "L":
            kinds.append("Boolean")
        elif ftype == "D":
            kinds.append("String")  # YYYYMMDD kept as text
        else:
            kinds.append("String")
    records: list[list] = []
    pos = hdr_size
    for _ in range(n_rec):
        if pos + rec_size > len(data):
            break
        rec = data[pos : pos + rec_size]
        pos += rec_size
        if rec[:1] == b"*":  # deleted
            records.append(None)
            continue
        vals: list = []
        off = 1
        for (name, ftype, length, decimals), kind in zip(fields, kinds):
            raw = rec[off : off + length].decode("latin-1").strip()
            off += length
            if kind == "Long":
                vals.append(int(raw) if raw and raw != "*" * length else 0)
            elif kind == "Double":
                vals.append(float(raw) if raw else float("nan"))
            elif kind == "Boolean":
                vals.append(raw.upper() in ("T", "Y"))
            else:
                vals.append(raw)
        records.append(vals)
    return names, kinds, records


def read_shapefile(
    shp: "bytes | str | IO",
    dbf: "bytes | str | IO | None" = None,
    type_name: str = "shp",
    geom_name: str = "geom",
) -> FeatureCollection:
    """Decode a shapefile (+ optional .dbf attributes) into a collection
    with an inferred schema. ``shp``/``dbf`` accept bytes, paths or file
    objects; when ``shp`` is a path and ``dbf`` is None, the sibling .dbf
    is picked up automatically."""

    def _bytes(src):
        if src is None:
            return None
        if isinstance(src, bytes):
            return src
        if isinstance(src, str):
            with open(src, "rb") as fh:
                return fh.read()
        return src.read()

    if isinstance(shp, str) and dbf is None:
        import os

        cand = shp[:-4] + ".dbf" if shp.lower().endswith(".shp") else None
        if cand and os.path.exists(cand):
            dbf = cand
    shapes = _read_shapes(_bytes(shp))
    names: list[str] = []
    kinds: list[str] = []
    records: Optional[list] = None
    d = _bytes(dbf)
    if d is not None:
        names, kinds, records = _read_dbf(d)
        if len(records) != len(shapes):
            raise ValueError(
                f".dbf has {len(records)} records but .shp has {len(shapes)} shapes"
            )

    keep = [
        i
        for i, s in enumerate(shapes)
        if s is not None and (records is None or records[i] is not None)
    ]
    gtype = "Geometry"
    ts = {type(shapes[i]).__name__ for i in keep}
    if len(ts) == 1:
        gtype = ts.pop()
    spec = ",".join(
        [f"{n}:{k}" for n, k in zip(names, kinds)] + [f"*{geom_name}:{gtype}:srid=4326"]
    )
    sft = FeatureType.from_spec(type_name, spec)
    rows = []
    for i in keep:
        row = {geom_name: shapes[i]}
        if records is not None:
            row.update(dict(zip(names, records[i])))
        rows.append(row)
    return FeatureCollection.from_rows(sft, rows, ids=[str(i) for i in keep])
