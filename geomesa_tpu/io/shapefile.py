"""Shapefile (.shp/.dbf) reader: ESRI shapefiles -> feature batches.

Reference: geomesa-convert-shp (/root/reference/geomesa-convert/
geomesa-convert-shp/src/main/scala/org/locationtech/geomesa/convert/shp/
ShapefileConverter.scala) — there it delegates to GeoTools' shapefile
store; here the format is decoded directly (no GDAL/fiona in the image):
the .shp geometry file (ESRI whitepaper layout: 100-byte header, BE
record headers, LE shapes) and the dBase III .dbf attribute file
(fixed-width ASCII records). Point/MultiPoint/PolyLine/Polygon shapes
map onto the packed geometry model; polygon ring winding (outer = CW in
shapefiles) splits shells from holes, holes attaching to the preceding
shell (the standard writer ordering).
"""

from __future__ import annotations

import struct
from typing import IO, Optional

import numpy as np

from geomesa_tpu import geometry as geo
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType

SHP_MAGIC = 9994

# shape type code -> handler name
_POINT = {1, 11, 21}  # Point / PointZ / PointM (Z/M dropped)
_POLYLINE = {3, 13, 23}
_POLYGON = {5, 15, 25}
_MULTIPOINT = {8, 18, 28}


def _ring_is_cw(ring: np.ndarray) -> bool:
    """Shoelace: negative signed area = clockwise = shapefile outer ring."""
    x, y = ring[:, 0], ring[:, 1]
    return float(np.sum(x[:-1] * y[1:] - x[1:] * y[:-1])) < 0


def _read_shapes(data: bytes) -> list:
    """.shp payload -> list of Geometry | None (null shapes)."""
    if len(data) < 100 or struct.unpack(">i", data[:4])[0] != SHP_MAGIC:
        raise ValueError("not a shapefile (.shp)")
    out: list = []
    pos = 100
    n = len(data)
    while pos + 8 <= n:
        (_recno, content_words) = struct.unpack(">ii", data[pos : pos + 8])
        pos += 8
        end = pos + content_words * 2
        if end > n:
            raise ValueError("truncated shapefile record")
        (stype,) = struct.unpack("<i", data[pos : pos + 4])
        body = data[pos + 4 : end]
        pos = end
        if stype == 0:
            out.append(None)
        elif stype in _POINT:
            x, y = struct.unpack_from("<2d", body, 0)
            out.append(geo.Point(x, y))
        elif stype in _MULTIPOINT:
            (npts,) = struct.unpack_from("<i", body, 32)
            pts = np.frombuffer(body, "<f8", count=npts * 2, offset=36).reshape(-1, 2)
            out.append(
                geo.MultiPoint([geo.Point(float(p[0]), float(p[1])) for p in pts])
            )
        elif stype in _POLYLINE or stype in _POLYGON:
            nparts, npts = struct.unpack_from("<2i", body, 32)
            parts = np.frombuffer(body, "<i4", count=nparts, offset=40)
            pts = np.frombuffer(
                body, "<f8", count=npts * 2, offset=40 + 4 * nparts
            ).reshape(-1, 2)
            bounds = np.append(parts, npts)
            rings = [
                np.array(pts[bounds[i] : bounds[i + 1]], dtype=np.float64)
                for i in range(nparts)
            ]
            if stype in _POLYLINE:
                lines = [geo.LineString(r) for r in rings if len(r) >= 2]
                out.append(
                    lines[0] if len(lines) == 1 else geo.MultiLineString(lines)
                )
            else:
                out.append(_assemble_polygon(rings))
        else:
            raise ValueError(f"unsupported shape type {stype}")
    return out


def _assemble_polygon(rings: list) -> "geo.Geometry":
    """CW rings open polygons, CCW rings are holes of the preceding shell
    (standard shapefile writer ordering)."""
    polys: list[tuple[np.ndarray, list]] = []
    for r in rings:
        if len(r) < 4:
            continue
        if _ring_is_cw(r) or not polys:
            polys.append((r[::-1].copy(), []))  # store shells CCW (WKT norm)
        else:
            polys[-1][1].append(r)
    if not polys:
        raise ValueError("polygon record with no valid rings")
    geoms = [geo.Polygon(shell, holes) for shell, holes in polys]
    return geoms[0] if len(geoms) == 1 else geo.MultiPolygon(geoms)


def _read_dbf(data: bytes) -> tuple[list[str], list[str], list[list]]:
    """dBase III file -> (field names, field types, record values)."""
    if len(data) < 32:
        raise ValueError("truncated .dbf")
    n_rec, hdr_size, rec_size = struct.unpack_from("<iHH", data, 4)
    fields = []
    pos = 32
    while pos < hdr_size - 1 and data[pos] != 0x0D:
        name = data[pos : pos + 11].split(b"\x00")[0].decode("ascii", "replace")
        ftype = chr(data[pos + 11])
        length = data[pos + 16]
        decimals = data[pos + 17]
        fields.append((name, ftype, length, decimals))
        pos += 32
    names = [f[0] for f in fields]
    kinds = []
    for _, ftype, _length, decimals in fields:
        if ftype in ("N", "F"):
            kinds.append("Double" if (decimals > 0 or ftype == "F") else "Long")
        elif ftype == "L":
            kinds.append("Boolean")
        elif ftype == "D":
            kinds.append("String")  # YYYYMMDD kept as text
        else:
            kinds.append("String")
    records: list[list] = []
    pos = hdr_size
    for _ in range(n_rec):
        if pos + rec_size > len(data):
            break
        rec = data[pos : pos + rec_size]
        pos += rec_size
        if rec[:1] == b"*":  # deleted
            records.append(None)
            continue
        vals: list = []
        off = 1
        for (name, ftype, length, decimals), kind in zip(fields, kinds):
            raw = rec[off : off + length].decode("latin-1").strip()
            off += length
            if kind == "Long":
                vals.append(int(raw) if raw and raw != "*" * length else 0)
            elif kind == "Double":
                vals.append(float(raw) if raw else float("nan"))
            elif kind == "Boolean":
                vals.append(raw.upper() in ("T", "Y"))
            else:
                vals.append(raw)
        records.append(vals)
    return names, kinds, records


def read_shapefile(
    shp: "bytes | str | IO",
    dbf: "bytes | str | IO | None" = None,
    type_name: str = "shp",
    geom_name: str = "geom",
) -> FeatureCollection:
    """Decode a shapefile (+ optional .dbf attributes) into a collection
    with an inferred schema. ``shp``/``dbf`` accept bytes, paths or file
    objects; when ``shp`` is a path and ``dbf`` is None, the sibling .dbf
    is picked up automatically."""

    def _bytes(src):
        if src is None:
            return None
        if isinstance(src, bytes):
            return src
        if isinstance(src, str):
            with open(src, "rb") as fh:
                return fh.read()
        return src.read()

    if isinstance(shp, str) and dbf is None:
        import os

        cand = shp[:-4] + ".dbf" if shp.lower().endswith(".shp") else None
        if cand and os.path.exists(cand):
            dbf = cand
    shapes = _read_shapes(_bytes(shp))
    names: list[str] = []
    kinds: list[str] = []
    records: Optional[list] = None
    d = _bytes(dbf)
    if d is not None:
        names, kinds, records = _read_dbf(d)
        if len(records) != len(shapes):
            raise ValueError(
                f".dbf has {len(records)} records but .shp has {len(shapes)} shapes"
            )

    keep = [
        i
        for i, s in enumerate(shapes)
        if s is not None and (records is None or records[i] is not None)
    ]
    gtype = "Geometry"
    ts = {type(shapes[i]).__name__ for i in keep}
    if len(ts) == 1:
        gtype = ts.pop()
    # the sibling .prj decides the srid stamp (written by write_shapefile;
    # Web-Mercator files must not round-trip mislabeled as degrees)
    srid = "4326"
    if isinstance(shp, str):
        import os

        prj = (shp[:-4] if shp.lower().endswith(".shp") else shp) + ".prj"
        if os.path.exists(prj):
            with open(prj, encoding="ascii", errors="replace") as fh:
                wkt = fh.read()
            if "Mercator" in wkt or "3857" in wkt:
                srid = "3857"
    spec = ",".join(
        [f"{n}:{k}" for n, k in zip(names, kinds)]
        + [f"*{geom_name}:{gtype}:srid={srid}"]
    )
    sft = FeatureType.from_spec(type_name, spec)
    if srid == "3857":
        sft.user_data["geomesa.crs"] = "EPSG:3857"
    rows = []
    for i in keep:
        row = {geom_name: shapes[i]}
        if records is not None:
            row.update(dict(zip(names, records[i])))
        rows.append(row)
    return FeatureCollection.from_rows(sft, rows, ids=[str(i) for i in keep])


# ------------------------------------------------------------------ write

_TYPE_CODE = {"Point": 1, "LineString": 3, "Polygon": 5, "MultiLineString": 3,
              "MultiPolygon": 5, "MultiPoint": 8}


def _shape_record(g) -> bytes:
    """One record's content (shape type + body), little-endian."""
    if isinstance(g, geo.Point):
        return struct.pack("<i2d", 1, g.x, g.y)
    if isinstance(g, geo.MultiPoint):
        pts = np.array([[p.x, p.y] for p in g.parts], dtype="<f8")
        x0, y0, x1, y1 = g.bounds()
        return (
            struct.pack("<i4di", 8, x0, y0, x1, y1, len(pts)) + pts.tobytes()
        )
    if isinstance(g, (geo.LineString, geo.MultiLineString)):
        parts = [g.coords] if isinstance(g, geo.LineString) else [
            p.coords for p in g.parts
        ]
        code = 3
    elif isinstance(g, (geo.Polygon, geo.MultiPolygon)):
        polys = [g] if isinstance(g, geo.Polygon) else list(g.parts)
        parts = []
        for p in polys:
            shell = np.asarray(p.shell, dtype=np.float64)
            if not _ring_is_cw(shell):  # shapefile outer rings are CW
                shell = shell[::-1]
            parts.append(shell)
            for h in p.holes:
                hole = np.asarray(h, dtype=np.float64)
                if _ring_is_cw(hole):  # holes are CCW
                    hole = hole[::-1]
                parts.append(hole)
        code = 5
    else:
        raise ValueError(f"cannot write {type(g).__name__} to a shapefile")
    pts = np.concatenate([np.asarray(p, dtype="<f8") for p in parts])
    offsets = np.cumsum([0] + [len(p) for p in parts[:-1]]).astype("<i4")
    x0, y0, x1, y1 = g.bounds()
    return (
        struct.pack("<i4d2i", code, x0, y0, x1, y1, len(parts), len(pts))
        + offsets.tobytes()
        + pts.tobytes()
    )


def _dbf_fields(sft: FeatureType, fc: FeatureCollection):
    """(name, dbf type, width, decimals, formatter) per attribute."""
    out = []
    seen: set = set()
    for a in sft.attributes:
        if a.is_geometry:
            continue
        name = a.name[:10]
        k = 0
        while name in seen:  # 10-char truncation can collide
            k += 1
            name = f"{a.name[:10 - len(str(k))]}{k}"
        seen.add(name)
        col = fc.columns[a.name]
        if a.type in ("Integer", "Int", "Long"):
            # width 20 holds any int64 including the sign
            out.append((a.name, name, "N", 20, 0, lambda v: f"{int(v):>20d}"))
        elif a.type in ("Float", "Double"):
            # general format: any double fits in 25 chars at 16 sig digits
            out.append(
                (a.name, name, "F", 25, 8, lambda v: f"{float(v):>25.16g}")
            )
        elif a.type == "Boolean":
            out.append(
                (a.name, name, "L", 1, 0, lambda v: "T" if v else "F")
            )
        elif a.type == "Date":
            from geomesa_tpu.io.exporters import date_str

            out.append((
                a.name, name, "C", 24, 0,
                lambda v: date_str(v)[:24].ljust(24),
            ))
        else:
            width = 1
            if len(col):
                width = min(
                    254, max(1, max(len(str(v)) for v in np.asarray(col)))
                )
            out.append((
                a.name, name, "C", width, 0,
                lambda v, w=width: str(v)[:w].ljust(w),
            ))
    return out


def write_shapefile(fc: FeatureCollection, base: str) -> None:
    """Write ``base``.shp/.shx/.dbf (reference ShapefileExporter,
    geomesa-feature-exporters). Geometries must share one shapefile type
    family (points, lines, or polygons); attributes go to the .dbf."""
    sft = fc.sft
    geoms = fc.geometries()
    if not geoms:
        raise ValueError("nothing to write")
    codes = {_TYPE_CODE[type(g).__name__] for g in geoms}
    if len(codes) > 1:
        raise ValueError("shapefile requires a single geometry type family")
    code = codes.pop()

    records = [_shape_record(g) for g in geoms]
    xs = np.array([g.bounds() for g in geoms])
    bbox = (xs[:, 0].min(), xs[:, 1].min(), xs[:, 2].max(), xs[:, 3].max())

    def header(file_words: int) -> bytes:
        return (
            struct.pack(">7i", SHP_MAGIC, 0, 0, 0, 0, 0, file_words)
            + struct.pack("<2i", 1000, code)
            + struct.pack("<8d", *bbox, 0.0, 0.0, 0.0, 0.0)
        )

    shp = bytearray()
    shx = bytearray()
    offset_words = 50  # header = 100 bytes
    for i, rec in enumerate(records):
        words = len(rec) // 2
        shx += struct.pack(">2i", offset_words, words)
        shp += struct.pack(">2i", i + 1, words) + rec
        offset_words += 4 + words
    with open(base + ".shp", "wb") as fh:
        fh.write(header(offset_words) + bytes(shp))
    with open(base + ".shx", "wb") as fh:
        fh.write(header(50 + 4 * len(records)) + bytes(shx))

    fields = _dbf_fields(sft, fc)
    rec_size = 1 + sum(f[3] for f in fields)
    hdr = bytearray(struct.pack(
        "<4BiHH20x", 3, 24, 1, 1, len(fc), 33 + 32 * len(fields), rec_size
    ))
    for _, name, ftype, width, dec, _fmt in fields:
        hdr += name.encode("ascii", "replace")[:10].ljust(11, b"\x00")
        hdr += ftype.encode() + b"\x00" * 4 + bytes([width, dec]) + b"\x00" * 14
    hdr += b"\x0d"
    body = bytearray()
    for i in range(len(fc)):
        body += b" "
        for attr, _name, _ftype, width, _dec, fmt in fields:
            cell = fmt(fc.columns[attr][i]).encode("latin-1", "replace")
            if len(cell) > width:
                raise ValueError(
                    f"value for {attr!r} exceeds its DBF width {width}"
                )
            body += cell.ljust(width)
    with open(base + ".dbf", "wb") as fh:
        fh.write(bytes(hdr) + bytes(body) + b"\x1a")

    # .prj: label the coordinates we actually wrote (a reprojected
    # collection stamps its CRS in user_data — crs.reproject_collection)
    crs = str(sft.user_data.get("geomesa.crs", "EPSG:4326"))
    with open(base + ".prj", "w", encoding="ascii") as fh:
        fh.write(_PRJ_WKT.get(crs, _PRJ_WKT["EPSG:4326"]))


# standard ESRI WKT strings for the supported CRSs
_PRJ_WKT = {
    "EPSG:4326": (
        'GEOGCS["GCS_WGS_1984",DATUM["D_WGS_1984",SPHEROID["WGS_1984",'
        '6378137.0,298.257223563]],PRIMEM["Greenwich",0.0],'
        'UNIT["Degree",0.0174532925199433]]'
    ),
    "EPSG:3857": (
        'PROJCS["WGS_1984_Web_Mercator_Auxiliary_Sphere",'
        'GEOGCS["GCS_WGS_1984",DATUM["D_WGS_1984",SPHEROID["WGS_1984",'
        '6378137.0,298.257223563]],PRIMEM["Greenwich",0.0],'
        'UNIT["Degree",0.0174532925199433]],'
        'PROJECTION["Mercator_Auxiliary_Sphere"],'
        'PARAMETER["False_Easting",0.0],PARAMETER["False_Northing",0.0],'
        'PARAMETER["Central_Meridian",0.0],'
        'PARAMETER["Standard_Parallel_1",0.0],'
        'PARAMETER["Auxiliary_Sphere_Type",0.0],UNIT["Meter",1.0]]'
    ),
}
