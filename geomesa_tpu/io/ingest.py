"""Parallel converter ingest: the sequential-commit distributed-ingest
driver (compatibility surface).

Reference: distributed MapReduce ingest (/root/reference/geomesa-jobs/src/
main/scala/org/locationtech/geomesa/jobs/mapreduce/ —
``ConverterInputFormat`` splits inputs, mappers run the converter,
``GeoMesaOutputFormat`` writes; driven by tools/ingest/IngestCommand.scala
which picks local vs distributed mode). Parsing fans out over a process
pool (one "mapper" per input split) while the single JAX controller stays
the only writer.

The split machinery (byte-range splits, the picklable converter config,
the guarded worker) now lives in :mod:`geomesa_tpu.ingest.splits`; this
module keeps the original *sequential-commit* driver — each split's batch
goes through ``store.write`` as it arrives, with the store's normal
incremental compaction cadence. The staged multi-core pipeline
(:mod:`geomesa_tpu.ingest.pipeline`) is the bulk-load path: deferred
single compaction, sharded sort, atomic publish. Use this one when you
want per-split incremental visibility; use the pipeline for throughput.

Worker failures surface as :class:`~geomesa_tpu.ingest.IngestError` with
the worker-side traceback, and per-split parse-error counts aggregate into
``IngestResult.split_errors`` ordered by split index (deterministic across
worker counts and completion orders).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

from geomesa_tpu.ingest.pipeline import (
    IngestError,
    IngestResult,
    raise_split_failure,
    rebase_ids,
)
from geomesa_tpu.ingest.splits import (  # noqa: F401 (compat re-exports)
    ConverterConfig,
    Split,
    SplitFailure,
    run_split_guarded,
)
from geomesa_tpu.ingest import splits as _splits

# a split per ~32 MB keeps task granularity reasonable for big files.
# Kept as a module-level knob here (tests/config patch it); the canonical
# default lives in geomesa_tpu.ingest.splits.
SPLIT_BYTES = _splits.SPLIT_BYTES


def plan_splits(
    paths: Sequence[str], fmt: str, split_bytes: int | None = None
) -> list[Split]:
    """Input files -> mapper splits (see ingest.splits.plan_splits).
    Defaults to THIS module's patchable ``SPLIT_BYTES``."""
    if split_bytes is None:
        split_bytes = SPLIT_BYTES  # read at call time so tests/config can tune
    return _splits.plan_splits(paths, fmt, split_bytes)


def _run_split(cfg: ConverterConfig, split: Split):
    """Mapper: parse one split -> (FeatureCollection, n_errors)."""
    return _splits.run_split(cfg, split)


def ingest_files(
    store,
    converter,
    paths: Sequence[str],
    workers: Optional[int] = None,
    id_prefix_splits: bool = True,
) -> IngestResult:
    """Convert ``paths`` with a pool of worker processes and write the
    results to ``store`` split by split. ``workers=0/1`` runs in-process
    (the reference's local ingest mode). ``id_prefix_splits`` namespaces
    running-index feature ids per split so converters without an id
    expression don't collide across splits."""
    cfg = ConverterConfig.of(converter)
    type_name = converter.sft.name
    splits = plan_splits(paths, converter.fmt)
    result = IngestResult(splits=len(splits))
    if workers is None:
        workers = min(len(splits), os.cpu_count() or 1)

    # running-index rebase: seed from the store ONCE (a features() call
    # concatenates all chunks — doing it per split would be quadratic),
    # then track the count locally; this writer is the only one
    base = (
        len(store.features(type_name))
        if id_prefix_splits and converter.id_field is None
        else 0
    )

    def commit(res):
        nonlocal base
        idx, fc, errors, reasons, _parse_s, failure = res
        if failure is not None:
            raise_split_failure(failure, splits)
        result.split_errors.append(errors)
        result.errors += errors
        result.add_reasons(reasons)
        if len(fc) == 0:
            return
        if id_prefix_splits and converter.id_field is None:
            fc = rebase_ids(fc, base)
            base += len(fc)
        result.written += store.write(type_name, fc)

    tasks = [(cfg, sp, i) for i, sp in enumerate(splits)]
    if workers <= 1 or len(splits) <= 1:
        for t in tasks:
            commit(run_split_guarded(t))
        return result

    import multiprocessing as mp

    ctx = mp.get_context("fork")
    with ctx.Pool(workers) as pool:
        # imap streams results in SPLIT order: commits overlap conversion,
        # only ~workers results are in flight (not the whole dataset), and
        # error aggregation is deterministic whatever order workers finish
        for res in pool.imap(run_split_guarded, tasks):
            commit(res)
    return result
