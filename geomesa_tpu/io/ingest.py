"""Parallel converter ingest: the distributed-ingest driver.

Reference: distributed MapReduce ingest (/root/reference/geomesa-jobs/src/
main/scala/org/locationtech/geomesa/jobs/mapreduce/ —
``ConverterInputFormat`` splits inputs, mappers run the converter,
``GeoMesaOutputFormat`` writes; driven by tools/ingest/IngestCommand.scala
which picks local vs distributed mode). The TPU-native inversion: parsing
and conversion — the CPU-bound stage — fan out over a process pool (one
"mapper" per input split), while the single JAX controller stays the only
writer (SURVEY §2.6: single-controller design, no distributed lock). Large
delimited files are split at line boundaries into byte-range tasks, so one
big CSV parallelizes like many small files.

Workers rebuild the converter from its config (compiled expressions hold
closures and cannot pickle); results return as columnar
FeatureCollections, and the driver writes batches in order — the LSM delta
tier makes each write O(batch).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional, Sequence

from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.io.converters import Converter, FieldSpec
from geomesa_tpu.sft import FeatureType

# a split per ~32 MB keeps task granularity reasonable for big files
SPLIT_BYTES = 32 << 20


@dataclass
class ConverterConfig:
    """Picklable converter description (the mapper-side job config)."""

    spec: str
    type_name: str
    fields: Sequence[tuple]  # (name, transform)
    id_field: Optional[str]
    fmt: str
    delimiter: str
    skip_lines: int
    drop_errors: bool
    xml_feature_tag: Optional[str]
    user_data: dict = field(default_factory=dict)

    @staticmethod
    def of(conv: Converter) -> "ConverterConfig":
        return ConverterConfig(
            spec=conv.sft.to_spec(),
            type_name=conv.sft.name,
            fields=[(f.name, f.transform) for f in conv.fields],
            id_field=conv.id_field,
            fmt=conv.fmt,
            delimiter=conv.delimiter,
            skip_lines=conv.skip_lines,
            drop_errors=conv.drop_errors,
            xml_feature_tag=conv.xml_feature_tag,
            user_data=dict(conv.sft.user_data),
        )

    def build(self) -> Converter:
        sft = FeatureType.from_spec(self.type_name, self.spec)
        sft.user_data.update(self.user_data)
        return Converter(
            sft=sft,
            fields=[FieldSpec(n, t) for n, t in self.fields],
            id_field=self.id_field,
            fmt=self.fmt,
            delimiter=self.delimiter,
            skip_lines=self.skip_lines,
            drop_errors=self.drop_errors,
            xml_feature_tag=self.xml_feature_tag,
        )


@dataclass(frozen=True)
class Split:
    """One mapper task: a byte range of one input file (the
    ConverterInputFormat split analogue). ``skip_header`` drops the
    configured header lines (first split of a delimited file only)."""

    path: str
    start: int
    end: int  # exclusive
    skip_header: bool


def plan_splits(
    paths: Sequence[str], fmt: str, split_bytes: int | None = None
) -> list[Split]:
    """Input files -> mapper splits. Only delimited files split mid-file
    (line-oriented); JSON/XML/Avro documents stay whole."""
    if split_bytes is None:
        split_bytes = SPLIT_BYTES  # read at call time so tests/config can tune
    out: list[Split] = []
    for path in paths:
        size = os.path.getsize(path)
        if fmt != "delimited" or size <= split_bytes:
            out.append(Split(path, 0, size, True))
            continue
        with open(path, "rb") as fh:
            start = 0
            while start < size:
                end = min(start + split_bytes, size)
                if end < size:  # advance to the next line boundary
                    fh.seek(end)
                    fh.readline()
                    end = fh.tell()
                out.append(Split(path, start, end, start == 0))
                start = end
    return out


def _run_split(cfg: ConverterConfig, split: Split):
    """Mapper: parse one split -> (FeatureCollection, n_errors)."""
    conv = cfg.build()
    if not split.skip_header:
        conv.skip_lines = 0
    with open(split.path, "rb") as fh:
        fh.seek(split.start)
        data = fh.read(split.end - split.start)
    fc = conv.convert(data)
    return fc, conv.errors


@dataclass
class IngestResult:
    written: int = 0
    errors: int = 0
    splits: int = 0


def ingest_files(
    store,
    converter: Converter,
    paths: Sequence[str],
    workers: Optional[int] = None,
    id_prefix_splits: bool = True,
) -> IngestResult:
    """Convert ``paths`` with a pool of worker processes and write the
    results to ``store``. ``workers=0/1`` runs in-process (the reference's
    local ingest mode). ``id_prefix_splits`` namespaces running-index
    feature ids per split so converters without an id expression don't
    collide across splits."""
    cfg = ConverterConfig.of(converter)
    type_name = converter.sft.name
    splits = plan_splits(paths, converter.fmt)
    result = IngestResult(splits=len(splits))
    if workers is None:
        workers = min(len(splits), os.cpu_count() or 1)

    # running-index rebase: seed from the store ONCE (a features() call
    # concatenates all chunks — doing it per split would be quadratic),
    # then track the count locally; this writer is the only one
    base = (
        len(store.features(type_name))
        if id_prefix_splits and converter.id_field is None
        else 0
    )

    def commit(fc, errors):
        nonlocal base
        result.errors += errors
        if len(fc) == 0:
            return
        if id_prefix_splits and converter.id_field is None:
            # running-index ids restart per split AND per run: rebase onto
            # the store's row count (same semantics as the sequential CLI
            # path), so repeat ingests and multi-split inputs never collide
            import numpy as np

            fc = FeatureCollection(
                fc.sft,
                np.arange(base, base + len(fc)).astype(str),
                fc.columns,
            )
            base += len(fc)
        result.written += store.write(type_name, fc)

    if workers <= 1 or len(splits) <= 1:
        for sp in splits:
            fc, errors = _run_split(cfg, sp)
            commit(fc, errors)
        return result

    import multiprocessing as mp

    ctx = mp.get_context("fork")
    with ctx.Pool(workers) as pool:
        # imap streams results in split order: commits overlap conversion
        # and only ~workers results are in flight (not the whole dataset)
        for fc, errors in pool.imap(_run_split_star, [(cfg, sp) for sp in splits]):
            commit(fc, errors)
    return result


def _run_split_star(args):
    return _run_split(*args)
