"""GeoJSON FeatureCollection reader — the ingest direction of the
GeoJSON exporter (io/exporters._geojson).

Reference: the JSON converter (geomesa-convert-json) covers arbitrary
JSON via JSONPath configs; RFC 7946 GeoJSON is self-describing, so this
reader needs no config: the schema is inferred from the properties of
the features (Int/Double/String, ISO-8601 strings become Dates) and the
geometry type, mirroring TypeInference for the delimited converter.
"""

from __future__ import annotations

import json
import re

import numpy as np

from geomesa_tpu import geometry as geo
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType

_ISO = re.compile(r"^\d{4}-\d{2}-\d{2}[T ]\d{2}:\d{2}(:\d{2}(\.\d+)?)?Z?$")


def _infer_attr_type(values: list) -> str:
    """Schema type for one property across all features (None skipped)."""
    vals = [v for v in values if v is not None]
    if not vals:
        return "String"
    if all(isinstance(v, bool) for v in vals):
        return "Boolean"
    if all(isinstance(v, int) and not isinstance(v, bool) for v in vals):
        return "Long" if any(abs(v) > (1 << 31) - 1 for v in vals) else "Int"
    if all(isinstance(v, (int, float)) and not isinstance(v, bool) for v in vals):
        return "Double"
    if all(isinstance(v, str) and _ISO.match(v) for v in vals):
        return "Date"
    return "String"


def read_geojson(
    source,
    type_name: str = "features",
    sft: "FeatureType | None" = None,
    id_offset: int = 0,
) -> FeatureCollection:
    """Decode a GeoJSON FeatureCollection (text, path, file-like, or an
    already-parsed dict). With ``sft`` None the schema is inferred and
    the geometry attribute is named ``geom``; with an explicit ``sft``
    the geometry key follows its schema. Features without an explicit
    ``id`` get running indices starting at ``id_offset`` (so repeat
    ingests can rebase on the store size)."""
    if isinstance(source, dict):
        obj = source
    elif isinstance(source, (str, bytes)) and not source.lstrip().startswith(
        "{" if isinstance(source, str) else b"{"
    ):
        with open(source) as f:
            obj = json.load(f)
    elif hasattr(source, "read"):
        obj = json.load(source)
    else:
        obj = json.loads(source)
    if obj.get("type") != "FeatureCollection":
        raise ValueError(f"not a GeoJSON FeatureCollection: {obj.get('type')!r}")
    feats = obj.get("features", [])

    from geomesa_tpu.sql.functions import _geom_from_geojson

    geoms = [
        _geom_from_geojson(f["geometry"]) if f.get("geometry") is not None else None
        for f in feats
    ]
    if any(g is None for g in geoms):
        raise ValueError("features without geometry are not supported")

    geom_name = sft.geom_field if sft is not None else "geom"
    prop_names: list[str] = []
    for f in feats:
        for k in (f.get("properties") or {}):
            if k not in prop_names and k != geom_name:
                prop_names.append(k)
    columns = {
        k: [(f.get("properties") or {}).get(k) for f in feats] for k in prop_names
    }

    if sft is None:
        all_points = all(isinstance(g, geo.Point) for g in geoms)
        gtype = "Point" if all_points else (
            geoms[0].geom_type if len({g.geom_type for g in geoms}) == 1
            else "Geometry"
        )
        parts = [f"{k}:{_infer_attr_type(v)}" for k, v in columns.items()]
        parts.append(f"*{geom_name}:{gtype}:srid=4326")
        sft = FeatureType.from_spec(type_name, ",".join(parts))

    # synthesized ids must not collide with explicit ids in the same batch
    # (a file mixing id-less features with explicit numeric ids): number
    # only the id-less features with a separate counter, skipping values
    # already taken by an explicit id
    explicit = {str(f["id"]) for f in feats if f.get("id") is not None}
    ids: list[str] = []
    next_id = id_offset
    for f in feats:
        if f.get("id") is not None:
            ids.append(str(f["id"]))
        else:
            while str(next_id) in explicit:
                next_id += 1
            ids.append(str(next_id))
            next_id += 1
    rows = []
    for i, f in enumerate(feats):
        row = dict(f.get("properties") or {})
        row[geom_name] = geoms[i]
        rows.append(row)
    return FeatureCollection.from_rows(sft, rows, ids=ids)
