"""IO tier: exporters and ingest converters (the geomesa-features
exporters + geomesa-convert analogue, SURVEY.md §2.3/§2.5)."""

from geomesa_tpu.io.exporters import export
from geomesa_tpu.io.converters import Converter, infer_schema

__all__ = ["export", "Converter", "infer_schema"]
