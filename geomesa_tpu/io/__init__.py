"""IO tier: exporters, ingest converters, and storage formats (the
geomesa-features exporters + geomesa-convert + geomesa-fs Parquet
analogue, SURVEY.md §2.3/§2.4/§2.5)."""

from geomesa_tpu.io.converters import Converter, dbapi_records, infer_schema
from geomesa_tpu.io.exporters import export

__all__ = ["export", "Converter", "dbapi_records", "infer_schema"]
