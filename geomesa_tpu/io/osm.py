"""OpenStreetMap XML converter (reference geomesa-convert-osm module;
implemented from the public OSM XML format: <node id lat lon> with
<tag k v/> children, <way id> with <nd ref/> + tags).

- ``kind="nodes"``: every tagged (or all) node becomes a Point feature;
- ``kind="ways"``: ways resolve their node refs into LineStrings, or
  Polygons when the ring closes and the way carries an area-ish tag
  (building/landuse/area=yes — the conventional OSM area heuristic).

Selected tag keys become string attributes (missing tags are empty).
"""

from __future__ import annotations

import io as _io
import xml.etree.ElementTree as ET
from typing import Sequence

import numpy as np

from geomesa_tpu import geometry as geo
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType

DEFAULT_TAGS = ("name", "highway", "building", "amenity", "landuse")
_AREA_KEYS = {"building", "landuse", "leisure", "natural", "amenity"}


def _root(src) -> ET.Element:
    if isinstance(src, bytes):
        return ET.fromstring(src.decode("utf-8"))
    if isinstance(src, str):
        if src.lstrip().startswith("<"):
            return ET.fromstring(src)
        with open(src, "rb") as fh:
            return ET.parse(fh).getroot()
    return ET.parse(src).getroot()


def _tags(el) -> dict:
    return {t.get("k"): t.get("v") for t in el.findall("tag")}


def read_osm(
    src,
    kind: str = "nodes",
    type_name: str = "osm",
    tags: Sequence[str] = DEFAULT_TAGS,
    tagged_only: bool = True,
) -> FeatureCollection:
    """Parse OSM XML into a FeatureCollection of nodes or ways.

    ``tagged_only`` (nodes): skip bare geometry-carrier nodes (the
    overwhelming majority in real extracts — they only exist to shape
    ways), matching the reference converter's default.
    """
    if kind not in ("nodes", "ways"):
        raise ValueError(f"kind must be nodes|ways, got {kind!r}")
    root = _root(src)

    if kind == "nodes":
        ids, lon, lat, cols = [], [], [], {k: [] for k in tags}
        for n in root.findall("node"):
            t = _tags(n)
            if tagged_only and not t:
                continue
            ids.append(str(n.get("id")))
            lon.append(float(n.get("lon")))
            lat.append(float(n.get("lat")))
            for k in tags:
                cols[k].append(t.get(k, ""))
        sft = FeatureType.from_spec(
            type_name,
            ",".join(f"{k}:String" for k in tags) + ",*geom:Point:srid=4326",
        )
        return FeatureCollection.from_columns(
            sft, np.array(ids),
            {**{k: np.array(v if v else [], dtype=object) for k, v in cols.items()},
             "geom": (np.array(lon, np.float64), np.array(lat, np.float64))},
        )

    # ways: resolve node refs (ALL nodes this time — carriers included)
    coords = {
        str(n.get("id")): (float(n.get("lon")), float(n.get("lat")))
        for n in root.findall("node")
    }
    ids, geoms, cols = [], [], {k: [] for k in tags}
    for w in root.findall("way"):
        refs = [str(nd.get("ref")) for nd in w.findall("nd")]
        pts = [coords[r] for r in refs if r in coords]
        if len(pts) < 2:
            continue
        t = _tags(w)
        closed = len(pts) >= 4 and pts[0] == pts[-1]
        is_area = closed and (
            t.get("area") == "yes" or any(k in t for k in _AREA_KEYS)
        )
        g = geo.Polygon(pts[:-1]) if is_area else geo.LineString(pts)
        ids.append(str(w.get("id")))
        geoms.append(g)
        for k in tags:
            cols[k].append(t.get(k, ""))
    sft = FeatureType.from_spec(
        type_name,
        ",".join(f"{k}:String" for k in tags) + ",*geom:Geometry:srid=4326",
    )
    return FeatureCollection.from_columns(
        sft, np.array(ids),
        {**{k: np.array(v if v else [], dtype=object) for k, v in cols.items()},
         "geom": geo.PackedGeometryColumn.from_geometries(geoms)},
    )
