"""Export sinks: CSV / TSV / GeoJSON / WKT lines / JSON rows / Arrow IPC.

Reference: the feature-exporter SPI (/root/reference/geomesa-features/
geomesa-feature-exporters/src/main/scala/org/locationtech/geomesa/
features/exporters/ — DelimitedExporter, GeoJsonExporter, ArrowExporter).
Columnar analogues: each sink renders whole columns. Arrow export uses
pyarrow when present and raises a clear error otherwise (the wheel is not
in every image).
"""

from __future__ import annotations

import io
import json
from typing import IO

import numpy as np

from geomesa_tpu import geometry as geo
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.filter.predicates import PointColumn

FORMATS = (
    "csv", "tsv", "geojson", "wkt", "json", "gml", "arrow", "avro",
    "parquet", "orc", "leaflet",
)


def export(fc: FeatureCollection, fmt: str, fh: IO | None = None) -> "str | bytes":
    """Render a collection in ``fmt``; writes to ``fh`` when given, and
    always returns the rendered payload (str, or bytes for arrow)."""
    fmt = fmt.lower()
    if fmt in ("csv", "tsv"):
        payload = _delimited(fc, "," if fmt == "csv" else "\t")
    elif fmt == "geojson":
        payload = _geojson(fc)
    elif fmt == "wkt":
        payload = _wkt_lines(fc)
    elif fmt == "json":
        payload = _json_rows(fc)
    elif fmt == "gml":
        payload = _gml(fc)
    elif fmt == "arrow":
        payload = _arrow(fc)
    elif fmt == "avro":
        from geomesa_tpu.io.avro import write_avro

        payload = write_avro(fc)
    elif fmt == "parquet":
        import io as _io

        from geomesa_tpu.io.parquet import write_parquet

        buf = _io.BytesIO()
        write_parquet(fc, buf)
        payload = buf.getvalue()
    elif fmt == "orc":
        import io as _io

        from geomesa_tpu.io.orc import write_orc

        buf = _io.BytesIO()
        write_orc(fc, buf)
        payload = buf.getvalue()
    elif fmt == "leaflet":
        payload = _leaflet(fc)
    else:
        raise ValueError(f"unknown format {fmt!r}; supported: {FORMATS}")
    if fh is not None:
        fh.write(payload)
    return payload


def _geom_strings(fc: FeatureCollection) -> "np.ndarray | None":
    col = fc.geom_column
    if col is None:
        return None
    if isinstance(col, PointColumn):
        return np.array(
            [f"POINT ({x:.10g} {y:.10g})" for x, y in zip(col.x, col.y)]
        )
    return np.array([geo.to_wkt(col.geometry(i)) for i in range(len(col))])


def _cell(v) -> str:
    if isinstance(v, (float, np.floating)):
        return f"{v:.10g}"
    return str(v)


def _date_strings(col) -> np.ndarray:
    """ISO-8601 rendering of an epoch-millis Date column."""
    return np.datetime_as_string(
        np.asarray(col, dtype=np.int64).astype("datetime64[ms]"), unit="ms"
    )


def date_str(v) -> str:
    """ISO-8601 'Z' rendering of one epoch-millis value — the single
    definition shared by the GML and DBF writers."""
    return f"{np.datetime64(int(v), 'ms')}Z"


def _delimited(fc: FeatureCollection, sep: str) -> str:
    geom_field = fc.sft.geom_field
    geoms = _geom_strings(fc)
    names = [a.name for a in fc.sft.attributes]
    types = {a.name: a.type for a in fc.sft.attributes}
    out = io.StringIO()
    out.write(sep.join(["id"] + names) + "\n")
    cols = []
    for n in names:
        if n == geom_field:
            cols.append(geoms)
        elif types[n] == "Date":
            cols.append(_date_strings(fc.columns[n]))
        else:
            cols.append(np.asarray(fc.columns[n]))
    for i in range(len(fc)):
        row = [str(fc.ids[i])] + [_cell(c[i]) for c in cols]
        out.write(sep.join(_quote(v, sep) for v in row) + "\n")
    return out.getvalue()


def _quote(v: str, sep: str) -> str:
    if sep in v or '"' in v or "\n" in v:
        return '"' + v.replace('"', '""') + '"'
    return v


def geojson_features(fc: FeatureCollection):
    """Per-feature GeoJSON dicts, in result order — the shared core of
    :func:`_geojson` and the served data plane's streamed writer
    (serving/http.py), so a paged network response is bit-identical to
    the one-shot export by construction."""
    geom_field = fc.sft.geom_field
    date_fields = {a.name for a in fc.sft.attributes if a.type == "Date"}
    for row in fc.to_rows():
        fid = row.pop("__id__")
        g = row.pop(geom_field, None)  # to_rows already decoded the geometry
        props = {
            k: (date_str(v) if k in date_fields and v is not None else _jsonable(v))
            for k, v in row.items()
        }
        yield {
            "type": "Feature",
            "id": fid,
            "geometry": _geojson_geom(g) if g is not None else None,
            "properties": props,
        }


def geojson_crs(fc: FeatureCollection) -> "dict | None":
    """The legacy named-CRS member for non-WGS84 collections (None for
    EPSG:4326). RFC 7946 mandates WGS84; reprojected collections carry
    the GeoJSON-2008 member so coordinates are not misread as degrees."""
    crs = str(fc.sft.user_data.get("geomesa.crs", "EPSG:4326"))
    if crs == "EPSG:4326":
        return None
    code = crs.split(":")[-1]
    return {
        "type": "name",
        "properties": {"name": f"urn:ogc:def:crs:EPSG::{code}"},
    }


def _geojson(fc: FeatureCollection) -> str:
    out = {"type": "FeatureCollection", "features": list(geojson_features(fc))}
    crs = geojson_crs(fc)
    if crs is not None:
        out["crs"] = crs
    return json.dumps(out)


def _geojson_geom(g: geo.Geometry) -> dict:
    def ring(r):
        return [[float(x), float(y)] for x, y in np.asarray(r)]

    if isinstance(g, geo.Point):
        return {"type": "Point", "coordinates": [g.x, g.y]}
    if isinstance(g, geo.LineString):
        return {"type": "LineString", "coordinates": ring(g.coords)}
    if isinstance(g, geo.Polygon):
        return {"type": "Polygon", "coordinates": [ring(g.shell)] + [ring(h) for h in g.holes]}
    if isinstance(g, geo.MultiPoint):
        return {"type": "MultiPoint", "coordinates": [[p.x, p.y] for p in g.parts]}
    if isinstance(g, geo.MultiLineString):
        return {"type": "MultiLineString", "coordinates": [ring(p.coords) for p in g.parts]}
    if isinstance(g, geo.MultiPolygon):
        return {
            "type": "MultiPolygon",
            "coordinates": [
                [ring(p.shell)] + [ring(h) for h in p.holes] for p in g.parts
            ],
        }
    raise TypeError(f"cannot render {type(g)}")


def _jsonable(v):
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, np.str_):
        return str(v)
    return v


def _wkt_lines(fc: FeatureCollection) -> str:
    geoms = _geom_strings(fc)
    if geoms is None:
        raise ValueError("schema has no geometry to export as WKT")
    return "\n".join(geoms.tolist()) + "\n"


def _json_rows(fc: FeatureCollection) -> str:
    geom_field = fc.sft.geom_field
    rows = []
    for row in fc.to_rows():
        if geom_field in row:
            row[geom_field] = geo.to_wkt(row[geom_field])
        rows.append({k: _jsonable(v) for k, v in row.items()})
    return json.dumps(rows)


def _arrow(fc: FeatureCollection) -> bytes:
    """Arrow IPC record-batch stream built from the store's columns, with
    dictionary-encoded string attributes (geomesa_tpu.io.arrow; reference
    ArrowScan.scala:31-240)."""
    from geomesa_tpu.io.arrow import arrow_stream

    return arrow_stream(fc)


def _gml_coords(coords) -> str:
    return " ".join(f"{x:.10g} {y:.10g}" for x, y in np.asarray(coords))


def _gml_geom(g: "geo.Geometry", srs: str = "EPSG:4326") -> str:
    """GML 3.1 geometry element (srsName from the collection's CRS,
    lon/lat order kept)."""
    if isinstance(g, geo.Point):
        return (
            f'<gml:Point srsName="{srs}"><gml:pos>{g.x:.10g} {g.y:.10g}'
            "</gml:pos></gml:Point>"
        )
    if isinstance(g, geo.LineString):
        return (
            f'<gml:LineString srsName="{srs}"><gml:posList>'
            f"{_gml_coords(g.coords)}</gml:posList></gml:LineString>"
        )
    if isinstance(g, geo.Polygon):
        rings = [
            "<gml:exterior><gml:LinearRing><gml:posList>"
            f"{_gml_coords(g.shell)}</gml:posList></gml:LinearRing></gml:exterior>"
        ]
        for h in g.holes:
            rings.append(
                "<gml:interior><gml:LinearRing><gml:posList>"
                f"{_gml_coords(h)}</gml:posList></gml:LinearRing></gml:interior>"
            )
        return (
            f'<gml:Polygon srsName="{srs}">{"".join(rings)}</gml:Polygon>'
        )
    if isinstance(g, (geo.MultiPoint, geo.MultiLineString, geo.MultiPolygon)):
        tag = {
            geo.MultiPoint: ("gml:MultiPoint", "gml:pointMember"),
            geo.MultiLineString: ("gml:MultiCurve", "gml:curveMember"),
            geo.MultiPolygon: ("gml:MultiSurface", "gml:surfaceMember"),
        }[type(g)]
        inner = "".join(
            f"<{tag[1]}>{_gml_geom(p, srs)}</{tag[1]}>" for p in g.parts
        )
        return f'<{tag[0]} srsName="{srs}">{inner}</{tag[0]}>'
    raise ValueError(f"cannot GML-encode {type(g).__name__}")


def _gml(fc: FeatureCollection) -> str:
    """GML 3.1 FeatureCollection (reference GmlExporter,
    geomesa-feature-exporters)."""
    from xml.sax.saxutils import escape, quoteattr

    sft = fc.sft
    name = escape(sft.name or "features")
    # a reprojected collection stamps its CRS in user_data (crs.py)
    srs = str(sft.user_data.get("geomesa.crs", "EPSG:4326"))
    geoms = fc.geometries()
    parts = [
        '<?xml version="1.0" encoding="UTF-8"?>\n'
        '<gml:FeatureCollection xmlns:gml="http://www.opengis.net/gml" '
        'xmlns:geomesa="http://geomesa.org">\n'
    ]
    for i in range(len(fc)):
        parts.append(
            f"<gml:featureMember><geomesa:{name} "
            f"gml:id={quoteattr(str(fc.ids[i]))}>"
        )
        for a in sft.attributes:
            if a.is_geometry:
                parts.append(
                    f"<geomesa:{a.name}>{_gml_geom(geoms[i], srs)}"
                    f"</geomesa:{a.name}>"
                )
                continue
            v = fc.columns[a.name][i]
            if a.type == "Date":
                v = date_str(v)
            parts.append(f"<geomesa:{a.name}>{escape(str(v))}</geomesa:{a.name}>")
        parts.append(f"</geomesa:{name}></gml:featureMember>\n")
    parts.append("</gml:FeatureCollection>\n")
    return "".join(parts)


def _leaflet(fc: FeatureCollection) -> str:
    """Self-contained Leaflet HTML map with the features inlined as a
    GeoJSON FeatureCollection (reference LeafletMapExporter: HTML shell +
    CDN leaflet + `var points = <geojson>` + a density-weighted heat
    layer; here the heat tint rides per-marker opacity)."""
    from xml.sax.saxutils import escape

    if str(fc.sft.user_data.get("geomesa.crs", "EPSG:4326")) != "EPSG:4326":
        # the Leaflet map template interprets coordinates as lon/lat
        # degrees; a reprojected collection would render at garbage
        # positions with no error
        raise ValueError(
            "leaflet export requires EPSG:4326 coordinates; drop the "
            "reproject hint"
        )
    # '</' must not appear literally inside the <script> block: a string
    # attribute containing '</script>' would otherwise terminate it and
    # inject attacker-controlled markup into the exported page
    gj = _geojson(fc).replace("</", "<\\/")
    xs, ys = (
        fc.representative_xy() if len(fc) and fc.sft.geom_field else ([0.0], [0.0])
    )
    cx = float(np.mean(np.asarray(ys))) if len(ys) else 0.0
    cy = float(np.mean(np.asarray(xs))) if len(xs) else 0.0
    title = escape(fc.sft.name)
    return f"""<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8"/>
<title>{title}</title>
<link rel="stylesheet" href="https://unpkg.com/leaflet@1.9.4/dist/leaflet.css"/>
<script src="https://unpkg.com/leaflet@1.9.4/dist/leaflet.js"></script>
<style>html, body, #map {{ height: 100%; margin: 0; }}</style>
</head>
<body>
<div id="map"></div>
<script>
var points = {gj};
var map = L.map('map').setView([{cx:.6f}, {cy:.6f}], 3);
L.tileLayer('https://{{s}}.tile.openstreetmap.org/{{z}}/{{x}}/{{y}}.png',
  {{ attribution: '&copy; OpenStreetMap contributors' }}).addTo(map);
var layer = L.geoJSON(points, {{
  pointToLayer: function (feature, latlng) {{
    return L.circleMarker(latlng, {{ radius: 4, weight: 1, fillOpacity: 0.6 }});
  }},
  onEachFeature: function (feature, l) {{
    var esc = function (s) {{
      return s.replace(/[&<>]/g, function (c) {{
        return {{'&': '&amp;', '<': '&lt;', '>': '&gt;'}}[c];
      }});
    }};
    // bindPopup renders HTML: attribute values must be escaped or a
    // hostile string attribute executes in the reader's browser
    l.bindPopup('<pre>' + esc(JSON.stringify(feature.properties, null, 1)) + '</pre>');
  }}
}}).addTo(map);
if (layer.getBounds().isValid()) {{ map.fitBounds(layer.getBounds()); }}
</script>
</body>
</html>
"""
