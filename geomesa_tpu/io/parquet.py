"""Parquet feature IO — the geomesa-fs storage-format analogue.

Reference: ParquetFileSystemStorage (/root/reference/geomesa-fs/
geomesa-fs-storage/geomesa-fs-storage-parquet/src/main/scala/org/
locationtech/geomesa/fs/storage/parquet/ParquetFileSystemStorage.scala,
SimpleFeatureParquetSchema.scala) — the reference's CPU baseline stores
features as Parquet files with an SFT-derived schema. Here the columnar
FeatureCollection maps straight onto Arrow arrays (io/arrow) and writes
through pyarrow.parquet; the SFT spec rides in the file metadata so a
read can reconstruct the schema without a catalog.

Predicate push-down (the reference's FilterConverter tier) comes from
pyarrow's own row-group filtering: ``read_parquet(..., bbox=...)`` turns
a bbox into column statistics filters on the point coordinate columns.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType

_SFT_KEY = b"geomesa.sft.spec"
_NAME_KEY = b"geomesa.sft.name"


def write_parquet(
    fc: FeatureCollection, path, compression: str = "zstd", row_group_rows: int = 1 << 20
) -> None:
    """Write a collection to one Parquet file. Point geometries become
    plain ``<geom>_x`` / ``<geom>_y`` double columns (so Parquet
    column statistics support bbox push-down); extent geometries a WKB
    binary column."""
    import pyarrow.parquet as pq

    from geomesa_tpu.io.arrow import flat_point_table

    table = flat_point_table(fc, dictionary=True)
    meta = dict(table.schema.metadata or {})
    meta[_SFT_KEY] = fc.sft.to_spec().encode()
    meta[_NAME_KEY] = fc.sft.name.encode()
    table = table.replace_schema_metadata(meta)
    pq.write_table(
        table, path, compression=compression, row_group_size=row_group_rows
    )


def read_parquet(
    path,
    sft: "FeatureType | None" = None,
    bbox: "tuple[float, float, float, float] | None" = None,
) -> FeatureCollection:
    """Read a Parquet file written by :func:`write_parquet` back into a
    FeatureCollection. ``bbox`` pushes a coordinate-range filter into the
    Parquet reader (row-group statistics pruning + row filtering) for
    point schemas — the FilterConverter push-down analogue."""
    import pyarrow.parquet as pq

    schema = pq.read_schema(path)  # footer only; the data reads once below
    meta = schema.metadata or {}
    if sft is None:
        spec = meta.get(_SFT_KEY)
        if spec is None:
            raise ValueError(
                "file has no geomesa.sft.spec metadata; pass sft explicitly"
            )
        sft = FeatureType.from_spec(
            meta.get(_NAME_KEY, b"features").decode(), spec.decode()
        )
    geom = sft.geom_field
    filters = None
    if bbox is not None:
        if f"{geom}_x" not in schema.names:
            raise ValueError("bbox push-down requires a point schema")
        x0, y0, x1, y1 = bbox
        filters = [
            (f"{geom}_x", ">=", x0), (f"{geom}_x", "<=", x1),
            (f"{geom}_y", ">=", y0), (f"{geom}_y", "<=", y1),
        ]
    table = pq.read_table(path, filters=filters)

    from geomesa_tpu.io.arrow import table_to_collection

    return table_to_collection(table, sft)
