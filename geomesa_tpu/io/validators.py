"""Converter-side feature validation: the CqlValidatorFactory analogue.

Reference: geomesa-convert's SimpleFeatureValidator / CqlValidatorFactory
(/root/reference/geomesa-convert/geomesa-convert-common/.../convert2/
validators/) — named validators configured per converter ("index",
"has-geo", "has-dtg", or a CQL expression), each rejecting a converted
feature with a REASON instead of a bare boolean. The TPU build replaces
the old ``drop_errors``-only behaviour with the same hook: validators run
on every converted row, failures count per reason
(``Converter.error_reasons`` -> ``IngestResult.error_reasons``), and
``drop_errors`` keeps deciding skip-vs-raise for both parse and
validation failures.

Built-ins (``parse_validators`` spec names):

- ``has-geo``  — the geometry attribute is present (non-None);
- ``z-bounds`` — geometry coordinates are finite and inside the Z2/Z3
  normalization domain (lon [-180, 180], lat [-90, 90]): out-of-bounds
  coordinates would silently clamp into edge index cells;
- ``has-dtg``  — the default date attribute is present (required to key
  a z3/xz3 index);
- ``index``    — the composite the reference defaults to: has-geo +
  z-bounds, plus has-dtg when the schema has a date field;
- ``none``     — no validation.

Custom validators are any object with ``name`` and
``validate(row) -> str | None`` (None = pass, else the failure reason).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

from geomesa_tpu import geometry as geo


@dataclass
class Validator:
    """One named validation rule over a converted row dict."""

    name: str
    fn: Callable  # row -> str | None (failure reason)

    def validate(self, row: dict) -> Optional[str]:
        return self.fn(row)


def _bounds_of(g: geo.Geometry) -> tuple[float, float, float, float]:
    return g.bounds()


def has_geo(sft) -> Validator:
    field = sft.geom_field

    def check(row):
        if field is None or row.get(field) is None:
            return "missing geometry"
        return None

    return Validator("has-geo", check)


def z_bounds(sft) -> Validator:
    """Geometry coordinates finite and inside the curve normalization
    domain — the reference's z-index validator: out-of-bounds values
    would clamp into edge cells and index under the wrong key."""
    import math

    field = sft.geom_field

    def check(row):
        g = row.get(field) if field else None
        if g is None:
            return None  # has-geo owns presence
        x0, y0, x1, y1 = _bounds_of(g)
        if not all(map(math.isfinite, (x0, y0, x1, y1))):
            return "non-finite coordinates"
        if x0 < -180.0 or x1 > 180.0:
            return "longitude outside [-180, 180]"
        if y0 < -90.0 or y1 > 90.0:
            return "latitude outside [-90, 90]"
        return None

    return Validator("z-bounds", check)


def has_dtg(sft) -> Validator:
    field = sft.dtg_field

    def check(row):
        if field is not None and row.get(field) is None:
            return "missing date"
        return None

    return Validator("has-dtg", check)


def attribute_required(name: str) -> Validator:
    """A custom per-attribute presence rule (the CQL ``x IS NOT NULL``
    shape the reference expresses through CqlValidatorFactory)."""

    def check(row):
        if row.get(name) is None:
            return f"missing attribute {name!r}"
        return None

    return Validator(f"required-{name}", check)


def parse_validators(spec, sft) -> list[Validator]:
    """Validator list from a converter config value: a comma-separated
    name string ("index", "has-geo,z-bounds", "none"), a sequence of
    names and/or Validator objects, or None (no validation)."""
    if spec is None:
        return []
    if isinstance(spec, str):
        names = [s.strip() for s in spec.split(",") if s.strip()]
    else:
        names = list(spec)
    out: list[Validator] = []
    for n in names:
        if isinstance(n, Validator) or (
            hasattr(n, "validate") and hasattr(n, "name")
        ):
            out.append(n)
        elif n == "none":
            continue
        elif n == "has-geo":
            out.append(has_geo(sft))
        elif n == "z-bounds":
            out.append(z_bounds(sft))
        elif n == "has-dtg":
            out.append(has_dtg(sft))
        elif n == "index":
            out.append(has_geo(sft))
            out.append(z_bounds(sft))
            if sft.dtg_field is not None:
                out.append(has_dtg(sft))
        elif n.startswith("required:"):
            out.append(attribute_required(n.split(":", 1)[1]))
        else:
            raise ValueError(f"unknown validator {n!r}")
    return out


def validator_spec(validators) -> "str | None":
    """The picklable spec form of a converter's ``validators`` value
    (the mapper-side job config ships names, not closures). Validator
    OBJECTS cannot cross the process boundary — converters using them
    must run in-process (workers <= 1), like the reference's
    non-serializable custom validators."""
    if validators is None:
        return None
    if isinstance(validators, str):
        return validators
    names: list[str] = []
    for v in validators:
        if isinstance(v, str):
            names.append(v)
        else:
            raise ValueError(
                "custom Validator objects are not picklable for "
                "multi-process ingest; pass validator NAMES or run with "
                "workers<=1"
            )
    return ",".join(names)
