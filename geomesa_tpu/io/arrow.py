"""Arrow columnar output: IPC record-batch streams built from the store's
own columns — no per-row re-encode.

Reference: the server-side Arrow push-down (ArrowScan, /root/reference/
geomesa-index-api/src/main/scala/org/locationtech/geomesa/index/iterators/
ArrowScan.scala:31-240) builds dictionary-encoded Arrow vectors inside
region servers and streams record batches; DeltaWriter (geomesa-arrow/
geomesa-arrow-gt/src/main/scala/org/locationtech/geomesa/arrow/io/
DeltaWriter.scala) merges per-batch dictionary deltas client-side. The
columnar store inverts the problem: scan hits arrive as *column slices*
(FeatureCollection.take is a numpy fancy-index of whole columns), so the
Arrow table is a zero/near-zero-copy view — string attributes dictionary-
encode via one np.unique pass (one unified dictionary instead of the
reference's delta protocol, which exists only because region servers
cannot see each other's batches), points become FixedSizeList<2 x f64>
vectors (the geomesa-arrow-jts point vector layout), and Dates become
timestamp[ms]. Python row objects are never materialized.
"""

from __future__ import annotations

from typing import IO

import numpy as np

from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.filter.predicates import PointColumn

BATCH_ROWS = 65536


def _pa():
    try:
        import pyarrow as pa
    except ImportError as e:  # pragma: no cover - depends on image contents
        raise RuntimeError("arrow export requires pyarrow, which is not installed") from e
    return pa


def _string_array(pa, col: np.ndarray):
    """A string column as a pyarrow array, preserving nulls (object arrays
    may hold None; numpy str arrays cannot)."""
    if col.dtype.kind == "O":
        return pa.array(col, pa.string(), from_pandas=True)
    return pa.array(col.astype(str))


def _dictionary_array(pa, col: np.ndarray):
    """Dictionary-encode a string column: values array [n_unique] + i32
    codes [n] (reference ArrowScan dictionary vectors); nulls stay null."""
    return _string_array(pa, col).dictionary_encode()


def _geometry_array(pa, fc: FeatureCollection):
    """Point columns -> FixedSizeList<2 x float64> (geomesa-arrow-jts point
    vectors); extent geometries -> WKB binary (per-row by nature)."""
    col = fc.geom_column
    if isinstance(col, PointColumn):
        xy = np.empty(2 * len(fc), dtype=np.float64)
        xy[0::2] = col.x
        xy[1::2] = col.y
        return pa.FixedSizeListArray.from_arrays(pa.array(xy), 2)
    from geomesa_tpu import geometry as geo

    return pa.array([geo.to_wkb(col.geometry(i)) for i in range(len(fc))], pa.binary())


def _id_array(pa, fc: FeatureCollection):
    ids = np.asarray(fc.ids)
    return (
        pa.array(ids.astype(str)) if ids.dtype.kind in ("U", "O", "S")
        else pa.array(ids)
    )


def _attr_array(pa, fc: FeatureCollection, a, dictionary: bool):
    """One attribute as a pyarrow array (shared by the one-shot table
    build and the delta writer, which substitutes its own accumulated
    dictionaries for string columns)."""
    if a.name == fc.sft.geom_field:
        return _geometry_array(pa, fc)
    col = np.asarray(fc.columns[a.name])
    if a.type == "Date":
        return pa.array(col.astype("datetime64[ms]"))
    if a.type in ("String", "UUID"):
        return _dictionary_array(pa, col) if dictionary else _string_array(pa, col)
    if a.type == "Bytes":
        return pa.array(list(col), pa.binary())
    return pa.array(col)


_SFT_KEY = b"geomesa.sft.spec"
_NAME_KEY = b"geomesa.sft.name"


def to_arrow_table(fc: FeatureCollection, dictionary: bool = True):
    """The collection as a pyarrow Table (store columns, no Python rows).
    The SFT spec rides in the schema metadata so IPC payloads are
    self-describing (read_arrow)."""
    pa = _pa()
    names = ["id"]
    arrays = [_id_array(pa, fc)]
    for a in fc.sft.attributes:
        names.append(a.name)
        arrays.append(_attr_array(pa, fc, a, dictionary))
    table = pa.table(dict(zip(names, arrays)))
    return table.replace_schema_metadata(
        {_SFT_KEY: fc.sft.to_spec().encode(), _NAME_KEY: fc.sft.name.encode()}
    )


def read_arrow(source, sft=None) -> FeatureCollection:
    """Decode an Arrow IPC stream written by :func:`arrow_stream` (or the
    delta writer) back into a FeatureCollection — the ingest direction of
    the Arrow interop path. ``source`` is bytes, a path, or a file-like;
    the SFT comes from the stream's schema metadata unless given."""
    import io as _io

    from geomesa_tpu.sft import FeatureType

    pa = _pa()
    import pyarrow.ipc as ipc

    opened = None
    if isinstance(source, (bytes, bytearray)):
        source = _io.BytesIO(source)
    elif isinstance(source, str):
        source = opened = open(source, "rb")
    try:
        with ipc.open_stream(source) as reader:
            table = reader.read_all()
    finally:
        if opened is not None:
            opened.close()
    meta = table.schema.metadata or {}
    if sft is None:
        spec = meta.get(_SFT_KEY)
        if spec is None:
            raise ValueError(
                "stream has no geomesa.sft.spec metadata; pass sft explicitly"
            )
        sft = FeatureType.from_spec(
            meta.get(_NAME_KEY, b"features").decode(), spec.decode()
        )
    return table_to_collection(table, sft)


def arrow_stream(
    fc: FeatureCollection,
    fh: IO | None = None,
    dictionary: bool = True,
    batch_rows: int = BATCH_ROWS,
) -> bytes:
    """Arrow IPC stream of ``fc`` in record batches of ``batch_rows``.

    One unified dictionary per string column (computed over all hits) is
    written once; batches reference it — the client never merges deltas.
    """
    pa = _pa()
    import pyarrow.ipc as ipc

    table = to_arrow_table(fc, dictionary=dictionary)
    sink = pa.BufferOutputStream()
    with ipc.new_stream(sink, table.schema) as w:
        for batch in table.to_batches(max_chunksize=batch_rows):
            w.write_batch(batch)
    payload = sink.getvalue().to_pybytes()
    if fh is not None:
        fh.write(payload)
    return payload


def read_arrow_table(data: bytes):
    """Parse an IPC stream back into a pyarrow Table (the low-level
    sibling of :func:`read_arrow`, which decodes to a FeatureCollection)."""
    pa = _pa()
    import pyarrow.ipc as ipc

    with ipc.open_stream(pa.py_buffer(data)) as r:
        return r.read_all()


class ArrowDeltaWriter:
    """Incremental Arrow IPC stream with dictionary DELTAS — the streaming
    counterpart of :func:`arrow_stream` for results that arrive in batches
    (reference DeltaWriter protocol, geomesa-arrow/.../io/DeltaWriter.scala:
    each batch ships only the dictionary values not seen in earlier
    batches; the reader accumulates).

    Per string column, a value->code map grows across ``write()`` calls;
    batches encode against the accumulated dictionary and pyarrow's
    ``emit_dictionary_deltas`` writes just the new tail. ``finish()``
    closes the stream and returns the full payload.
    """

    def __init__(self, sft, batch_rows: int = BATCH_ROWS):
        self.sft = sft
        self.batch_rows = batch_rows
        self._pa = _pa()
        self._sink = self._pa.BufferOutputStream()
        self._writer = None
        # per string column: accumulated values list + value -> code,
        # plus the cached pyarrow dictionary array (appended, not rebuilt)
        self._dicts: dict[str, tuple[list, dict]] = {}
        self._dict_arrays: dict = {}
        self._string_cols = [
            a.name for a in sft.attributes
            if a.type in ("String", "UUID") and not a.is_geometry
        ]

    def _encode_batch(self, fc: FeatureCollection):
        pa = self._pa
        names = ["id"]
        arrays = [_id_array(pa, fc)]
        for a in fc.sft.attributes:
            names.append(a.name)
            if a.name in self._string_cols:
                arrays.append(self._delta_dictionary(a.name, fc))
            else:
                arrays.append(_attr_array(pa, fc, a, dictionary=False))
        return pa.table(dict(zip(names, arrays)))

    def _delta_dictionary(self, name: str, fc: FeatureCollection):
        """Encode one string column against the accumulated dictionary.
        Nulls (None/NaN in object arrays) stay null slots, never
        dictionary values — matching _string_array's null handling. The
        pyarrow dictionary array is cached and only the new tail is
        appended per batch (rebuilding it from the python list made total
        work quadratic over a long stream)."""
        pa = self._pa
        values, codes_of = self._dicts.setdefault(name, ([], {}))
        raw = np.asarray(fc.columns[name])
        null = (
            np.array(
                [
                    v is None or (isinstance(v, float) and np.isnan(v))
                    for v in raw
                ],
                dtype=bool,
            )
            if raw.dtype.kind == "O" else np.zeros(len(raw), dtype=bool)
        )
        codes = np.zeros(len(raw), dtype=np.int32)
        present = raw[~null]
        n_before = len(values)
        if len(present):
            u, inv = np.unique(present.astype(str), return_inverse=True)
            code_of_u = np.empty(len(u), dtype=np.int32)
            for j, v in enumerate(u.tolist()):  # uniques only
                c = codes_of.get(v)
                if c is None:
                    c = codes_of[v] = len(values)
                    values.append(v)
                code_of_u[j] = c
            codes[~null] = code_of_u[inv]
        cached = self._dict_arrays.get(name)
        if cached is None or len(values) != len(cached):
            tail = pa.array(values[n_before:], pa.string())
            cached = tail if cached is None else pa.concat_arrays([cached, tail])
            self._dict_arrays[name] = cached
        return pa.DictionaryArray.from_arrays(pa.array(codes, mask=null), cached)

    def write(self, fc: FeatureCollection) -> None:
        pa = self._pa
        table = self._encode_batch(fc)
        if self._writer is None:
            # same self-describing metadata as to_arrow_table, so delta
            # streams round-trip through read_arrow without an sft
            schema = table.schema.with_metadata(
                {_SFT_KEY: self.sft.to_spec().encode(),
                 _NAME_KEY: self.sft.name.encode()}
            )
            self._writer = pa.ipc.new_stream(
                self._sink, schema,
                options=pa.ipc.IpcWriteOptions(emit_dictionary_deltas=True),
            )
        for batch in table.to_batches(max_chunksize=self.batch_rows):
            self._writer.write_batch(batch)

    def finish(self) -> bytes:
        if self._writer is not None:
            self._writer.close()
        return self._sink.getvalue().to_pybytes()


def flat_point_table(fc: FeatureCollection, dictionary: bool = True):
    """Arrow table with point geometries flattened to ``<geom>_x`` /
    ``<geom>_y`` double columns — the shared layout of the Parquet and
    ORC writers (flat columns carry per-group/stripe statistics; nested
    FixedSizeList columns do not)."""
    import numpy as np

    from geomesa_tpu.filter.predicates import PointColumn

    pa = _pa()
    table = to_arrow_table(fc, dictionary=dictionary)
    geom = fc.sft.geom_field
    if geom is not None and isinstance(fc.geom_column, PointColumn):
        i = table.schema.get_field_index(geom)
        table = table.remove_column(i)
        col = fc.geom_column
        table = table.append_column(f"{geom}_x", pa.array(np.asarray(col.x)))
        table = table.append_column(f"{geom}_y", pa.array(np.asarray(col.y)))
    return table


def table_to_collection(table, sft) -> FeatureCollection:
    """Decode an arrow Table in the flat_point_table layout back into a
    FeatureCollection — the single reader shared by the Parquet and ORC
    formats (point x/y or WKB geometry, Date millis, dictionary or plain
    strings, Bytes blobs)."""
    import numpy as np

    from geomesa_tpu import geometry as geo

    geom = sft.geom_field
    cols: dict = {}
    for a in sft.attributes:
        if a.name == geom:
            if f"{geom}_x" in table.column_names:  # flat parquet/orc layout
                cols[geom] = (
                    np.asarray(table[f"{geom}_x"], dtype=np.float64),
                    np.asarray(table[f"{geom}_y"], dtype=np.float64),
                )
                continue
            arr = table[geom].combine_chunks()
            import pyarrow as pa

            if pa.types.is_fixed_size_list(arr.type):  # IPC point vectors
                xy = np.asarray(arr.flatten(), dtype=np.float64)
                cols[geom] = (xy[0::2], xy[1::2])
            else:  # WKB binary
                cols[geom] = geo.PackedGeometryColumn.from_geometries(
                    [geo.from_wkb(b) for b in arr.to_pylist()]
                )
            continue
        arr = table[a.name]
        if a.type == "Date":
            cols[a.name] = np.asarray(arr).astype("datetime64[ms]").astype(np.int64)
        elif a.type in ("String", "UUID", "Bytes"):
            a2 = arr.combine_chunks()
            try:  # dictionary-encoded on write (parquet)
                a2 = a2.dictionary_decode()
            except AttributeError:
                pass
            cols[a.name] = np.asarray(a2.to_pylist(), dtype=object)
        else:
            cols[a.name] = np.asarray(arr)
    return FeatureCollection.from_columns(sft, np.asarray(table["id"]), cols)
