"""Arrow columnar output: IPC record-batch streams built from the store's
own columns — no per-row re-encode.

Reference: the server-side Arrow push-down (ArrowScan, /root/reference/
geomesa-index-api/src/main/scala/org/locationtech/geomesa/index/iterators/
ArrowScan.scala:31-240) builds dictionary-encoded Arrow vectors inside
region servers and streams record batches; DeltaWriter (geomesa-arrow/
geomesa-arrow-gt/src/main/scala/org/locationtech/geomesa/arrow/io/
DeltaWriter.scala) merges per-batch dictionary deltas client-side. The
columnar store inverts the problem: scan hits arrive as *column slices*
(FeatureCollection.take is a numpy fancy-index of whole columns), so the
Arrow table is a zero/near-zero-copy view — string attributes dictionary-
encode via one np.unique pass (one unified dictionary instead of the
reference's delta protocol, which exists only because region servers
cannot see each other's batches), points become FixedSizeList<2 x f64>
vectors (the geomesa-arrow-jts point vector layout), and Dates become
timestamp[ms]. Python row objects are never materialized.
"""

from __future__ import annotations

from typing import IO

import numpy as np

from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.filter.predicates import PointColumn

BATCH_ROWS = 65536


def _pa():
    try:
        import pyarrow as pa
    except ImportError as e:  # pragma: no cover - depends on image contents
        raise RuntimeError("arrow export requires pyarrow, which is not installed") from e
    return pa


def _string_array(pa, col: np.ndarray):
    """A string column as a pyarrow array, preserving nulls (object arrays
    may hold None; numpy str arrays cannot)."""
    if col.dtype.kind == "O":
        return pa.array(col, pa.string(), from_pandas=True)
    return pa.array(col.astype(str))


def _dictionary_array(pa, col: np.ndarray):
    """Dictionary-encode a string column: values array [n_unique] + i32
    codes [n] (reference ArrowScan dictionary vectors); nulls stay null."""
    return _string_array(pa, col).dictionary_encode()


def _geometry_array(pa, fc: FeatureCollection):
    """Point columns -> FixedSizeList<2 x float64> (geomesa-arrow-jts point
    vectors); extent geometries -> WKB binary (per-row by nature)."""
    col = fc.geom_column
    if isinstance(col, PointColumn):
        xy = np.empty(2 * len(fc), dtype=np.float64)
        xy[0::2] = col.x
        xy[1::2] = col.y
        return pa.FixedSizeListArray.from_arrays(pa.array(xy), 2)
    from geomesa_tpu import geometry as geo

    return pa.array([geo.to_wkb(col.geometry(i)) for i in range(len(fc))], pa.binary())


def to_arrow_table(fc: FeatureCollection, dictionary: bool = True):
    """The collection as a pyarrow Table (store columns, no Python rows)."""
    pa = _pa()
    names = ["id"]
    arrays = [
        pa.array(np.asarray(fc.ids, dtype=str))
        if np.asarray(fc.ids).dtype.kind in ("U", "O", "S")
        else pa.array(np.asarray(fc.ids))
    ]
    geom_field = fc.sft.geom_field
    for a in fc.sft.attributes:
        names.append(a.name)
        if a.name == geom_field:
            arrays.append(_geometry_array(pa, fc))
            continue
        col = np.asarray(fc.columns[a.name])
        if a.type == "Date":
            arrays.append(pa.array(col.astype("datetime64[ms]")))
        elif a.type in ("String", "UUID"):
            arrays.append(
                _dictionary_array(pa, col) if dictionary else _string_array(pa, col)
            )
        elif a.type == "Bytes":
            arrays.append(pa.array(list(col), pa.binary()))
        else:
            arrays.append(pa.array(col))
    return pa.table(dict(zip(names, arrays)))


def arrow_stream(
    fc: FeatureCollection,
    fh: IO | None = None,
    dictionary: bool = True,
    batch_rows: int = BATCH_ROWS,
) -> bytes:
    """Arrow IPC stream of ``fc`` in record batches of ``batch_rows``.

    One unified dictionary per string column (computed over all hits) is
    written once; batches reference it — the client never merges deltas.
    """
    pa = _pa()
    import pyarrow.ipc as ipc

    table = to_arrow_table(fc, dictionary=dictionary)
    sink = pa.BufferOutputStream()
    with ipc.new_stream(sink, table.schema) as w:
        for batch in table.to_batches(max_chunksize=batch_rows):
            w.write_batch(batch)
    payload = sink.getvalue().to_pybytes()
    if fh is not None:
        fh.write(payload)
    return payload


def read_arrow(data: bytes):
    """Parse an IPC stream back into a pyarrow Table (tests/consumers)."""
    pa = _pa()
    import pyarrow.ipc as ipc

    with ipc.open_stream(pa.py_buffer(data)) as r:
        return r.read_all()
